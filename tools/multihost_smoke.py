#!/usr/bin/env python
"""Multihost coordination smoke (tools/ci_check.sh).

A 2-process CPU cluster over a tmpdir store proves the coordination
substrate end to end, no TPU and no jax.distributed needed:

* both ranks publish heartbeats and complete a host-0 **rendezvous**
  round trip (leader publishes a token, the follower must read that
  exact token back);
* each rank records a fault and publishes its telemetry registry; the
  parent then runs the **host-0 merge** and asserts the merged
  Prometheus textfile + fault log carry BOTH ranks' labels;
* after the ranks exit (heartbeats go stale), a **watchdog process**
  running the cluster quorum scan must detect the quorum stall and
  exit NONZERO — the exit code a production supervisor would key a
  relaunch on. A watchdog that stays green while every rank is silent
  fails the smoke.

Usage: python tools/multihost_smoke.py           (run the smoke)
       python tools/multihost_smoke.py --child   (internal: one rank)
       python tools/multihost_smoke.py --watch   (internal: watchdog)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WATCH_STALL_EXIT = 3


def _child():
    sys.path.insert(0, REPO)
    from paddle_tpu.distributed import coordination
    from paddle_tpu.runtime import telemetry
    from paddle_tpu.runtime.resilience import record_fault

    ctx = coordination.cluster_context()
    assert ctx is not None
    coordination.init_cluster_telemetry(ctx)
    for step in range(3):
        coordination.publish_heartbeat(ctx.store, ctx.rank, step)
        time.sleep(0.1)
    if ctx.is_leader:
        token = coordination.rendezvous(ctx.store, "smoke_token",
                                        {"token": "tok-42"}, leader=True)
    else:
        token = coordination.rendezvous(ctx.store, "smoke_token",
                                        timeout=20.0)
    assert token == {"token": "tok-42"}, token
    record_fault("rollbacks", f"smoke fixture rank {ctx.rank}")
    telemetry.counter("paddle_tpu_train_steps_total", "steps").inc(
        ctx.rank + 1)
    telemetry.publish_registry(ctx.store, ctx.rank)
    print(f"CHILD_OK rank={ctx.rank}", flush=True)


def _watch():
    sys.path.insert(0, REPO)
    from paddle_tpu.distributed import coordination
    from paddle_tpu.distributed.elastic import ElasticManager

    ctx = coordination.cluster_context()
    em = ElasticManager(tempfile.mkdtemp(), timeout=600.0, cluster=ctx,
                        peer_stale_after=1.0, peer_dead_after=30.0)

    def on_stall(info):
        print(f"QUORUM_STALL reason={info.get('reason')} "
              f"stale={info.get('stale')}", flush=True)
        os._exit(WATCH_STALL_EXIT)

    em.start_watchdog(on_stall=on_stall, poll=0.2)
    deadline = time.monotonic() + 20.0
    step = 0
    while time.monotonic() < deadline:
        # the watchdog judges peers only while its own rank is ticking
        # (a non-participant is not entitled to call the cluster
        # wedged) — the watcher heartbeats as its own live rank
        em.tick(step)
        step += 1
        time.sleep(0.2)
    print("WATCHDOG_NEVER_FIRED", flush=True)
    sys.exit(0)  # green while the cluster is silent = smoke failure


def _env(cluster_dir, rank, world):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PADDLE_TPU_CLUSTER_DIR": cluster_dir,
                "PADDLE_TPU_CLUSTER_RANK": str(rank),
                "PADDLE_TPU_CLUSTER_WORLD": str(world)})
    return env


def main():
    if "--child" in sys.argv:
        _child()
        return
    if "--watch" in sys.argv:
        _watch()
        return

    sys.path.insert(0, REPO)
    cluster_dir = tempfile.mkdtemp(prefix="paddle_tpu_mh_smoke_")
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_env(cluster_dir, rank, 2)) for rank in range(2)]
    for rank, p in enumerate(procs):
        out, _ = p.communicate(timeout=180)
        out = out.decode("utf-8", "replace")
        assert p.returncode == 0, f"rank {rank} rc={p.returncode}:\n{out}"
        assert f"CHILD_OK rank={rank}" in out, out
    print("smoke: heartbeat + rendezvous round trip OK")

    from paddle_tpu.distributed.coordination import DirectoryStore
    from paddle_tpu.runtime import telemetry

    store = DirectoryStore(cluster_dir)
    merged = telemetry.merge_cluster(store)
    assert merged["ranks"] == [0, 1], merged["ranks"]
    parsed = telemetry.parse_prometheus_textfile(merged["prom_path"])
    ranks = {dict(labels).get("rank") for _, labels in parsed}
    assert {"0", "1"} <= ranks, ranks
    fault_ranks = {f["rank"] for f in merged["faults"]
                   if f["fault"] == "rollbacks"}
    assert fault_ranks == {0, 1}, merged["faults"]
    with open(merged["faults_path"]) as f:
        assert len([json.loads(line) for line in f]) >= 2
    print("smoke: host-0 merged prom + fault log carry both ranks OK")

    # both ranks have exited: their heartbeats are stale. The quorum
    # watchdog — running as a live THIRD rank, since a rank only judges
    # peers while ticking itself — must fire and exit nonzero within
    # its deadline (quorum over world 3 = 2 stale ranks).
    watch = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--watch"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_env(cluster_dir, 2, 3))
    out, _ = watch.communicate(timeout=60)
    out = out.decode("utf-8", "replace")
    assert watch.returncode == WATCH_STALL_EXIT, \
        f"watchdog rc={watch.returncode} (wanted {WATCH_STALL_EXIT}):\n{out}"
    assert "QUORUM_STALL reason=quorum_stale" in out, out
    print("smoke: quorum stall detected, watchdog exited nonzero OK")
    print("multihost_smoke: OK")


if __name__ == "__main__":
    main()
