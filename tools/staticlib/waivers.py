"""Inline waiver comments: `# <tool>: ok` / `# <tool>: ok[rule,...]`.

A waiver on the flagged line records a human review AT THE SITE (vs the
baseline, which records accepted debt in a side file). Rules can be
named by slug or id; a bare `ok` waives every rule on that line.
"""
from __future__ import annotations

import re

__all__ = ["suppressed"]

_CACHE = {}


def _pattern(tool):
    pat = _CACHE.get(tool)
    if pat is None:
        pat = re.compile(
            rf"#\s*{re.escape(tool)}:\s*ok(\[([A-Za-z0-9_,\- ]+)\])?")
        _CACHE[tool] = pat
    return pat


def suppressed(lines, lineno, rule, tool, rules):
    """True when source line `lineno` carries a waiver for `rule`.
    `rules` is the tool's slug->Rule catalog (for id aliasing)."""
    if not 1 <= lineno <= len(lines):
        return False
    m = _pattern(tool).search(lines[lineno - 1])
    if not m:
        return False
    if m.group(2) is None:
        return True
    waived = {s.strip() for s in m.group(2).split(",")}
    return rule in waived or rules[rule].id in waived
