"""Module-local call graph: who calls whom, resolvable file-locally.

Both analyzers need the same walk: tracelint to decide whether a trace
site can reach a `dispatch.suspend()` helper, threadlint to decide
which functions run on a thread-entry path and which locks are held at
a call site. The graph is deliberately file-local and approximate —
the same contract as the analyzers themselves: it must never import
the code it inspects, and unresolvable calls (cross-module, dynamic)
simply contribute no edge.

Resolution covers:
  * bare names — lexical scope search via ScopeIndex.resolve_function
    (module-level defs, nested defs, lambdas assigned to names);
  * ``self.m(...)`` / ``cls.m(...)`` — methods of the nearest enclosing
    class;
  * ``ClassName.m(...)`` — methods of a module-level class.
"""
from __future__ import annotations

import ast

__all__ = ["CallGraph"]

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class CallGraph:
    def __init__(self, tree, scopes):
        self.tree = tree
        self.scopes = scopes
        # qualname -> def node (lambdas keyed by their scope qualname)
        self.functions = {}
        self.node_qual = {}
        # class name -> {method name -> node}
        self.classes = {}
        for node in ast.walk(tree):
            if isinstance(node, _FUNC_NODES):
                qual = scopes.qualname(node)
                # first binding wins (redefinitions are rare and the
                # graph is approximate anyway)
                self.functions.setdefault(qual, node)
                self.node_qual[id(node)] = qual
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        methods[stmt.name] = stmt
                self.classes.setdefault(node.name, methods)
        # edges: caller qual -> [(call node, callee qual)]
        self.edges = {q: [] for q in self.functions}
        self._callers = {q: [] for q in self.functions}
        for qual, fnode in self.functions.items():
            for n in self.body_nodes(fnode):
                if not isinstance(n, ast.Call):
                    continue
                callee = self.resolve_call(n)
                if callee is not None:
                    self.edges[qual].append((n, callee))
                    self._callers[callee].append((qual, n))

    # -- iteration ----------------------------------------------------------
    @staticmethod
    def body_nodes(fnode):
        """Every node in `fnode`'s own body, NOT descending into nested
        def/lambda bodies (those are separate graph nodes)."""
        if isinstance(fnode, ast.Lambda):
            roots = [fnode.body]
        else:
            roots = list(fnode.body)
        stack = list(roots)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, _FUNC_NODES):
                continue  # the def itself is visible, its body is not
            for child in ast.iter_child_nodes(n):
                stack.append(child)

    # -- resolution ---------------------------------------------------------
    def resolve_target(self, expr, from_node):
        """Resolve a callable EXPRESSION (a call's func, or a callback
        argument like a Thread target) to a function qualname, or None."""
        if isinstance(expr, ast.Lambda):
            return self.node_qual.get(id(expr))
        if isinstance(expr, ast.Name):
            node = self.scopes.resolve_function(expr.id, from_node)
            if node is not None:
                return self.node_qual.get(id(node))
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            recv = expr.value.id
            if recv in ("self", "cls"):
                cdef = self.scopes.enclosing_class(from_node)
                if cdef is not None:
                    m = self.classes.get(cdef.name, {}).get(expr.attr)
                    if m is not None:
                        return self.node_qual.get(id(m))
                return None
            m = self.classes.get(recv, {}).get(expr.attr)
            if m is not None:
                return self.node_qual.get(id(m))
        return None

    def resolve_call(self, call):
        return self.resolve_target(call.func, call)

    def callers(self, qual):
        """[(caller qual, call node)] for locally-resolved call sites."""
        return self._callers.get(qual, [])

    def callees(self, qual):
        return self.edges.get(qual, [])

    # -- reachability -------------------------------------------------------
    def reachable(self, seeds):
        """Transitive closure of callees from `seeds` (qualnames),
        seeds included."""
        seen = set()
        stack = [s for s in seeds if s in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            for _, callee in self.edges.get(q, ()):
                if callee not in seen:
                    stack.append(callee)
        return seen
