"""Baseline (suppression) file handling, shared across analyzers.

The baseline is a checked-in multiset of finding fingerprints —
`rule|path|qualname|symbol`, deliberately line-number-free so edits
above a finding don't churn it.  CI fails only on findings whose
fingerprint count EXCEEDS the baselined count: pre-existing debt is
visible (reported as "baselined") but non-blocking, while any new
hazard, or a second instance of an old one, gates.

Fixing a baselined finding leaves a dangling fingerprint; the report
lists those as "stale baseline entries" so `--write-baseline` runs
shrink the file monotonically toward zero.
"""
from __future__ import annotations

import collections
import json
import os

BASELINE_VERSION = 1


def load_baseline(path):
    """fingerprint -> allowed count. Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return dict(data.get("fingerprints", {}))


def write_baseline(path, findings, comment):
    """Snapshot current non-suppressed, non-info findings as the new
    baseline (info findings never gate, so baselining them is noise).
    `comment` is the tool's regenerate hint, embedded in the file."""
    counts = collections.Counter(
        f.fingerprint() for f in findings
        if not f.suppressed and f.severity != "info")
    data = {
        "version": BASELINE_VERSION,
        "comment": comment,
        "fingerprints": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return counts


def partition(findings, baseline):
    """Split findings into (new, baselined, suppressed, info) and compute
    stale baseline fingerprints. `new` is what should gate CI."""
    new, baselined, suppressed, info = [], [], [], []
    budget = dict(baseline)
    for f in findings:
        if f.suppressed:
            suppressed.append(f)
            continue
        if f.severity == "info":
            info.append(f)
            continue
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp, n in budget.items() if n > 0)
    return new, baselined, suppressed, info, stale
