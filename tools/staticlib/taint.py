"""Name-level forward taint with a pluggable sanitizer vocabulary.

Extracted from tracelint's op-body checker: positional parameters
without defaults are assumed tainted (for tracelint: traced arrays);
parameters with defaults and closure statics are assumed clean. A
configurable sanitizer vocabulary (attribute reads, call heads,
coercions) launders taint — for tracelint these are the reads that are
static under a jax trace (``.shape``, ``len()``, ``isinstance()``);
another tool can bind its own vocabulary without touching the
propagation machinery.

The pass is iterated to a small fixpoint over simple assignments; it
is deliberately approximate (no aliasing, no containers) — precision
comes from each tool's confidence grading and checked baseline, not
from a heavier analysis.
"""
from __future__ import annotations

import ast

from .astnav import dotted, func_params

__all__ = ["NameTaint", "body_nodes"]


def body_nodes(fnode):
    """Every node under `fnode`'s body, nested defs INCLUDED (the
    tracelint contract: a nested helper's hazards belong to the op
    body that defines it)."""
    if isinstance(fnode, ast.Lambda):
        yield from ast.walk(fnode.body)
    else:
        for stmt in fnode.body:
            yield from ast.walk(stmt)


class NameTaint:
    """Per-function name-level taint state + queries.

    `static_attrs` — attribute reads that launder taint;
    `sanitizer_calls` — call heads whose result is clean regardless of
    argument taint; `coercions`/`host_methods` — calls whose RESULT is
    clean (the call itself may be a hazard, reported separately by the
    tool's own visitors).
    """

    def __init__(self, fnode, static_attrs=frozenset(),
                 sanitizer_calls=frozenset(), coercions=frozenset(),
                 host_methods=frozenset()):
        self.fnode = fnode
        self.static_attrs = static_attrs
        self.sanitizer_calls = sanitizer_calls
        self.coercions = coercions
        self.host_methods = host_methods

        self.params, self.tainted = func_params(fnode)
        self.vararg = fnode.args.vararg.arg if fnode.args.vararg else None
        self.locals = set(self.params)
        self._collect_locals()
        self.propagate()

    def _body_nodes(self):
        yield from body_nodes(self.fnode)

    def _collect_locals(self):
        for n in self._body_nodes():
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.locals.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.locals.add(n.name)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        self.locals.add(t.id)

    def propagate(self):
        """Name-level forward taint, iterated to a small fixpoint."""
        for _ in range(3):
            changed = False
            for n in self._body_nodes():
                tgts = None
                if isinstance(n, ast.Assign):
                    tgts, val = n.targets, n.value
                elif isinstance(n, ast.AugAssign):
                    tgts, val = [n.target], n.value
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    tgts, val = [n.target], n.value
                elif isinstance(n, ast.NamedExpr):
                    tgts, val = [n.target], n.value
                if not tgts or not self.expr_tainted(val):
                    continue
                for t in tgts:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) \
                                and nm.id not in self.tainted:
                            self.tainted.add(nm.id)
                            changed = True
            if not changed:
                break

    # -- queries ------------------------------------------------------------
    def expr_tainted(self, node):
        if node is None:
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in self.static_attrs:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and (d[-1] in self.sanitizer_calls
                      or d[-1] in self.coercions
                      or d[-1] in self.host_methods):
                return False  # result is clean (the call itself may be
                #               a hazard, reported separately)
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if self.expr_tainted(a):
                    return True
            # method call: the receiver's taint flows to the result
            # (x.astype(...) is as tainted as x)
            if isinstance(node.func, ast.Attribute):
                return self.expr_tainted(node.func.value)
            return False
        if isinstance(node, ast.Name):
            # the *args TUPLE is a host object (its truthiness/len are
            # clean); only its ELEMENTS carry taint
            if node.id == self.vararg:
                return False
            return node.id in self.tainted
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == self.vararg:
            return True
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # `x is None` is an identity test on the HOST object — a
            # tainted value is never None, so the test is clean
            return False
        for child in ast.iter_child_nodes(node):
            if self.expr_tainted(child):
                return True
        return False

    def taint_names(self, node):
        return sorted({n.id for n in ast.walk(node)
                       if isinstance(n, ast.Name) and n.id in self.tainted})
