"""AST navigation shared by every analyzer: dotted-name resolution,
runtime line accounting, parameter classification, lexical scope
chains, and tree iteration. Extracted verbatim from tracelint's
analyzer so both tools (and every future one) agree on what a
qualname, a traced parameter, or a resolvable local function IS."""
from __future__ import annotations

import ast
import os

__all__ = ["dotted", "runtime_first_line", "func_params", "ScopeIndex",
           "iter_py_files", "relpath", "DEFAULT_SKIP_DIRS", "const_range"]


def dotted(node):
    """('jax','jit') for jax.jit, ('x',) for x; None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def runtime_first_line(node):
    """co_firstlineno of the code object this def/lambda compiles to:
    for decorated defs that is the FIRST DECORATOR line, not the `def`
    line (CPython 3.8+ ast puts .lineno on the def)."""
    decs = getattr(node, "decorator_list", None)
    if decs:
        return min([d.lineno for d in decs] + [node.lineno])
    return node.lineno


def func_params(node):
    """(all param names, names assumed TRACED). Params with defaults are
    assumed static — the codebase idiom rides statics in via defaults
    (`lambda x, axis=axis: ...`) and arrays positionally."""
    a = node.args
    names, traced = [], set()
    pos = list(a.posonlyargs) + list(a.args)
    n_def = len(a.defaults)
    for i, p in enumerate(pos):
        names.append(p.arg)
        if i < len(pos) - n_def:
            traced.add(p.arg)
    if a.vararg:
        names.append(a.vararg.arg)
        traced.add(a.vararg.arg)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        names.append(p.arg)
        if d is None:
            traced.add(p.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names, traced


class ScopeIndex:
    """Parent links + lexical scope chains for one module AST."""

    def __init__(self, tree):
        self.parent = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.tree = tree

    def scope_chain(self, node):
        """Enclosing FunctionDef/AsyncFunctionDef/Lambda/ClassDef nodes,
        innermost first (the node itself excluded)."""
        out = []
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                out.append(cur)
            cur = self.parent.get(cur)
        return out

    def qualname(self, node):
        parts = []
        for s in [node] + self.scope_chain(node):
            if isinstance(s, ast.Lambda):
                parts.append("<lambda>")
            else:
                parts.append(s.name)
        return ".".join(reversed(parts))

    def enclosing_class(self, node):
        """Nearest enclosing ClassDef, or None."""
        for s in self.scope_chain(node):
            if isinstance(s, ast.ClassDef):
                return s
        return None

    def enclosing_loops(self, node):
        """Enclosing For/While statements (and comprehension generators)
        within the SAME function scope, innermost first. A node inside a
        loop body runs once per iteration — the loop-context query
        fuselint's per-step rules are built on. Stops at the nearest
        def/lambda boundary: an inner function's body is not "in" its
        definer's loop (it runs when called, not per iteration)."""
        out = []
        cur = self.parent.get(node)
        child = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                # the iter/test expression itself evaluates once (For)
                # or per-iteration (While) — count only BODY membership
                # for For so `for x in expensive()` isn't "in" the loop
                if not (isinstance(cur, (ast.For, ast.AsyncFor))
                        and child is cur.iter):
                    out.append(cur)
            elif isinstance(cur, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                # the FIRST generator's iter evaluates once, in the
                # enclosing scope — same exemption as For.iter above
                # (ancestry check: the chain to a nested node passes
                # through the `comprehension` node, not `iter` itself)
                it0 = cur.generators[0].iter
                if not any(sub is node for sub in ast.walk(it0)):
                    out.append(cur)
            child = cur
            cur = self.parent.get(cur)
        return out

    def loop_depth(self, node):
        return len(self.enclosing_loops(node))

    def resolve_function(self, name, from_node):
        """Find the def/lambda a bare name refers to at `from_node`,
        searching enclosing function scopes innermost-out, then module
        level. Returns the AST node or None."""
        scopes = [s for s in self.scope_chain(from_node)
                  if not isinstance(s, ast.ClassDef)]
        scopes.append(self.tree)
        for scope in scopes:
            body = scope.body if not isinstance(scope, ast.Lambda) else []
            hit = None
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == name:
                    hit = stmt
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name \
                                and isinstance(stmt.value, ast.Lambda):
                            hit = stmt.value
            if hit is not None:
                return hit
        return None


def const_range(call):
    """The statically-known trip count of a `range(...)` call, or None.
    Only constant int arguments resolve — `range(n)` is dynamic."""
    if not (isinstance(call, ast.Call) and dotted(call.func) == ("range",)):
        return None
    vals = []
    for a in call.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, int):
            vals.append(a.value)
        else:
            return None
    if len(vals) == 1:
        return max(0, vals[0])
    if len(vals) == 2:
        return max(0, vals[1] - vals[0])
    if len(vals) == 3 and vals[2] != 0:
        span = vals[1] - vals[0]
        step = vals[2]
        return max(0, (span + (step - (1 if step > 0 else -1))) // step)
    return None


DEFAULT_SKIP_DIRS = frozenset({"__pycache__", ".git", "libs", "include"})


def iter_py_files(root, skip_dirs=DEFAULT_SKIP_DIRS):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def relpath(path, root_parent):
    rel = os.path.relpath(path, root_parent)
    return rel.replace(os.sep, "/")
