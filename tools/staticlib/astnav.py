"""AST navigation shared by every analyzer: dotted-name resolution,
runtime line accounting, parameter classification, lexical scope
chains, and tree iteration. Extracted verbatim from tracelint's
analyzer so both tools (and every future one) agree on what a
qualname, a traced parameter, or a resolvable local function IS."""
from __future__ import annotations

import ast
import os

__all__ = ["dotted", "runtime_first_line", "func_params", "ScopeIndex",
           "iter_py_files", "relpath", "DEFAULT_SKIP_DIRS"]


def dotted(node):
    """('jax','jit') for jax.jit, ('x',) for x; None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def runtime_first_line(node):
    """co_firstlineno of the code object this def/lambda compiles to:
    for decorated defs that is the FIRST DECORATOR line, not the `def`
    line (CPython 3.8+ ast puts .lineno on the def)."""
    decs = getattr(node, "decorator_list", None)
    if decs:
        return min([d.lineno for d in decs] + [node.lineno])
    return node.lineno


def func_params(node):
    """(all param names, names assumed TRACED). Params with defaults are
    assumed static — the codebase idiom rides statics in via defaults
    (`lambda x, axis=axis: ...`) and arrays positionally."""
    a = node.args
    names, traced = [], set()
    pos = list(a.posonlyargs) + list(a.args)
    n_def = len(a.defaults)
    for i, p in enumerate(pos):
        names.append(p.arg)
        if i < len(pos) - n_def:
            traced.add(p.arg)
    if a.vararg:
        names.append(a.vararg.arg)
        traced.add(a.vararg.arg)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        names.append(p.arg)
        if d is None:
            traced.add(p.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names, traced


class ScopeIndex:
    """Parent links + lexical scope chains for one module AST."""

    def __init__(self, tree):
        self.parent = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.tree = tree

    def scope_chain(self, node):
        """Enclosing FunctionDef/AsyncFunctionDef/Lambda/ClassDef nodes,
        innermost first (the node itself excluded)."""
        out = []
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                out.append(cur)
            cur = self.parent.get(cur)
        return out

    def qualname(self, node):
        parts = []
        for s in [node] + self.scope_chain(node):
            if isinstance(s, ast.Lambda):
                parts.append("<lambda>")
            else:
                parts.append(s.name)
        return ".".join(reversed(parts))

    def enclosing_class(self, node):
        """Nearest enclosing ClassDef, or None."""
        for s in self.scope_chain(node):
            if isinstance(s, ast.ClassDef):
                return s
        return None

    def resolve_function(self, name, from_node):
        """Find the def/lambda a bare name refers to at `from_node`,
        searching enclosing function scopes innermost-out, then module
        level. Returns the AST node or None."""
        scopes = [s for s in self.scope_chain(from_node)
                  if not isinstance(s, ast.ClassDef)]
        scopes.append(self.tree)
        for scope in scopes:
            body = scope.body if not isinstance(scope, ast.Lambda) else []
            hit = None
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == name:
                    hit = stmt
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name \
                                and isinstance(stmt.value, ast.Lambda):
                            hit = stmt.value
            if hit is not None:
                return hit
        return None


DEFAULT_SKIP_DIRS = frozenset({"__pycache__", ".git", "libs", "include"})


def iter_py_files(root, skip_dirs=DEFAULT_SKIP_DIRS):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def relpath(path, root_parent):
    rel = os.path.relpath(path, root_parent)
    return rel.replace(os.sep, "/")
