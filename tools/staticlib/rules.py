"""Rule catalog machinery shared by every analyzer.

A catalog is data, not behavior — detection lives in each tool's
analyzer — so docs, reports and baselines speak one vocabulary per
tool. Severity vocabulary (shared so CI gating is uniform):

  error    — proven hazard.
  warning  — likely hazard; depends on runtime context.
  info     — hygiene note; never gates CI.
"""
from __future__ import annotations

import dataclasses

__all__ = ["Rule", "ruleset"]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str            # short numeric handle, e.g. "TL001" / "CL001"
    slug: str          # stable kebab-case name used in reports/baseline
    severity: str      # "error" | "warning" | "info"
    manifest: bool = False  # tool-specific: definite findings feed a
    #                         generated artifact (tracelint's unjittable
    #                         manifest); False for tools without one
    summary: str = ""


def ruleset(rules):
    """(RULES by slug, BY_ID, get) for a list of Rule objects."""
    by_slug = {r.slug: r for r in rules}
    by_id = {r.id: r for r in rules}

    def get(slug_or_id):
        return by_slug.get(slug_or_id) or by_id[slug_or_id]

    return by_slug, by_id, get
