"""The Finding record every analyzer emits.

Subclass per tool, binding the tool's rule catalog:

    class Finding(staticlib.Finding):
        RULES = RULES      # slug -> Rule (for rule_id lookup)

The fingerprint is deliberately line-number-free
(``rule|path|qualname|symbol``) so baselines survive unrelated edits
above a finding — the contract tracelint's baseline established and
every tool inherits.
"""
from __future__ import annotations

import dataclasses

__all__ = ["Finding"]


@dataclasses.dataclass
class Finding:
    rule: str           # rule slug from the tool's catalog
    path: str           # posix path relative to the analysis root's parent
    line: int
    col: int
    func: str           # dotted qualname of the enclosing scope ("" = module)
    func_name: str      # runtime co_name ("<lambda>" for lambdas)
    func_line: int      # runtime co_firstlineno of the enclosing scope
    message: str
    symbol: str         # short stable token for fingerprinting
    severity: str
    confidence: str     # "definite" | "possible"
    context: str        # tool-specific context tag
    suppressed: bool = False

    RULES = {}  # class-level: each tool's subclass binds its catalog

    @property
    def rule_id(self):
        return type(self).RULES[self.rule].id

    def fingerprint(self):
        """Line-number-free identity: survives unrelated edits above the
        finding, so the baseline doesn't churn with the file."""
        return f"{self.rule}|{self.path}|{self.func}|{self.symbol}"

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["rule_id"] = self.rule_id
        d["fingerprint"] = self.fingerprint()
        return d
