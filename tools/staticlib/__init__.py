"""staticlib — the shared core every repo static analyzer is built on.

Extracted from tracelint (PR 2) when threadlint arrived: two analyzers
were about to carry two copies of the same harness — AST navigation +
lexical scope resolution, a name-level taint pass with a pluggable
sanitizer vocabulary, a module-local call-graph walker, line-number-free
fingerprint baselines, inline `# <tool>: ok[rule]` waivers, and the
human/JSON report + CI exit-code contract. All of that lives here, so a
new analyzer (a sharding-spec checker, an API-deprecation scanner) is a
RULE CATALOG plus a detection visitor, not a new harness.

Layout:

  astnav     dotted-name/scope/param helpers, ScopeIndex, file iteration
  callgraph  module-local call graph (defs, methods, nested defs) with
             call-site records and reachability closure
  taint      name-level forward taint with configurable sanitizer sets
  rules      Rule dataclass + ruleset() registry helper
  findings   Finding dataclass: fingerprinting + JSON encoding
  baseline   fingerprint-multiset baseline: load / write / partition
  waivers    inline `# <tool>: ok[rule,...]` suppression comments
  report     human + machine-readable reports, parameterized by tool

Consumers: tools/tracelint (jit-safety), tools/threadlint (concurrency).
Everything is stdlib-only and must never import the code it analyzes.
"""
from .astnav import (  # noqa: F401
    DEFAULT_SKIP_DIRS, ScopeIndex, const_range, dotted, func_params,
    iter_py_files, relpath, runtime_first_line,
)
from .baseline import (  # noqa: F401
    BASELINE_VERSION, load_baseline, partition, write_baseline,
)
from .callgraph import CallGraph  # noqa: F401
from .findings import Finding  # noqa: F401
from .rules import Rule, ruleset  # noqa: F401
from .taint import NameTaint, body_nodes  # noqa: F401
from .waivers import suppressed  # noqa: F401

__all__ = [
    "DEFAULT_SKIP_DIRS", "ScopeIndex", "const_range", "dotted",
    "func_params", "iter_py_files", "relpath", "runtime_first_line",
    "BASELINE_VERSION", "load_baseline", "partition", "write_baseline",
    "CallGraph", "Finding", "Rule", "ruleset", "NameTaint", "body_nodes",
    "suppressed",
]

__version__ = "1.0"
