"""Human + machine-readable reporting, shared across analyzers.

Every function takes the TOOL name and its RULES catalog so the text a
developer reads names the right command and waiver syntax, while the
structure (what gates, what collapses to counts) is identical across
tools — one report grammar to learn, N analyzers.
"""
from __future__ import annotations

import collections
import json

REPORT_VERSION = 1


def format_finding(f, tag=""):
    tag = f" [{tag}]" if tag else ""
    where = f"{f.path}:{f.line}:{f.col + 1}"
    func = f" in `{f.func}`" if f.func else ""
    return (f"{where}: {f.rule_id} {f.rule} ({f.severity}/"
            f"{f.confidence}){tag}{func}\n    {f.message}")


def human_report(new, baselined, suppressed, info, stale, errors,
                 tool, rules, verbose=False):
    """Report text. `new` findings are always itemized (they gate);
    baselined/suppressed/info collapse to counts unless verbose."""
    out = []
    for f in new:
        out.append(format_finding(f, "NEW"))
    if verbose:
        for f in baselined:
            out.append(format_finding(f, "baselined"))
        for f in suppressed:
            out.append(format_finding(f, "waived"))
        for f in info:
            out.append(format_finding(f, "info"))
    for path, msg in errors:
        out.append(f"{path}: PARSE ERROR — {msg}")
    if stale:
        out.append(f"stale baseline entries ({len(stale)}) — fixed debt; "
                   "shrink the file with --write-baseline:")
        for fp in stale[:20]:
            out.append(f"    {fp}")
        if len(stale) > 20:
            out.append(f"    ... and {len(stale) - 20} more")

    by_rule = collections.Counter(f.rule for f in new + baselined)
    summary = (f"{tool}: {len(new)} new, {len(baselined)} baselined, "
               f"{len(suppressed)} waived inline, {len(info)} info, "
               f"{len(errors)} parse errors")
    if by_rule:
        summary += " | " + ", ".join(
            f"{rules[r].id} {r}: {n}" for r, n in sorted(by_rule.items()))
    out.append(summary)
    if new:
        out.append("FAIL: new findings above — fix them, waive with "
                   f"`# {tool}: ok[rule]` after review, or (for "
                   "accepted debt) refresh the baseline with "
                   "--write-baseline.")
    return "\n".join(out)


def json_report(new, baselined, suppressed, info, stale, errors, rules,
                extra=None):
    payload = {
        "version": REPORT_VERSION,
        "summary": {
            "new": len(new), "baselined": len(baselined),
            "suppressed": len(suppressed), "info": len(info),
            "parse_errors": len(errors), "stale_baseline": len(stale),
        },
        "rules": {slug: {"id": r.id, "severity": r.severity,
                         "manifest": r.manifest, "summary": r.summary}
                  for slug, r in sorted(rules.items())},
        "findings": {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed": [f.to_dict() for f in suppressed],
            "info": [f.to_dict() for f in info],
        },
        "stale_baseline": stale,
        "parse_errors": [{"path": p, "message": m} for p, m in errors],
    }
    if extra:
        payload.update(extra)
    return payload


def write_json(path, payload):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")


# ---------------------------------------------------------------------------
# SARIF 2.1.0 — the code-scanning interchange format CI annotates PRs
# from. One exporter for every analyzer: the tool name/rules bind per
# call, the structure is identical, so tracelint/threadlint/fuselint
# findings all surface as inline annotations through one pipeline.

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

_SARIF_LEVEL = {"error": "error", "warning": "warning", "info": "note"}


def sarif_report(new, baselined, suppressed, info, errors, tool, rules,
                 tool_version="1.0"):
    """Findings as one SARIF run. Gating semantics ride along:
    baselined/waived findings are emitted with SARIF suppressions (so
    code scanning shows them resolved, not new), `new` findings are
    unsuppressed, and parse errors become tool execution
    notifications."""
    def result(f, suppression=None):
        r = {
            "ruleId": f.rule_id,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
                "logicalLocations": [{"fullyQualifiedName": f.func}],
            }],
            "partialFingerprints": {"staticlibFingerprint/v1":
                                    f.fingerprint()},
        }
        if suppression is not None:
            r["suppressions"] = [{"kind": suppression[0],
                                  "justification": suppression[1]}]
        return r

    results = [result(f) for f in new]
    results += [result(f, ("external", "accepted debt in the checked "
                           "baseline")) for f in baselined]
    results += [result(f, ("inSource", f"reviewed inline `# {tool}: "
                           "ok[...]` waiver")) for f in suppressed]
    results += [result(f) for f in info]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "version": tool_version,
                "informationUri":
                    f"docs/{tool.upper()}.md",
                "rules": [{
                    "id": r.id,
                    "name": slug,
                    "shortDescription": {"text": slug},
                    "fullDescription": {"text": r.summary},
                    "defaultConfiguration": {
                        "level": _SARIF_LEVEL.get(r.severity, "warning")},
                } for slug, r in sorted(rules.items())],
            }},
            "invocations": [{
                "executionSuccessful": not errors,
                "toolExecutionNotifications": [{
                    "level": "error",
                    "message": {"text": f"{p}: PARSE ERROR — {m}"},
                } for p, m in errors],
            }],
            "results": results,
        }],
    }


def write_sarif(path, new, baselined, suppressed, info, errors, tool,
                rules, tool_version="1.0"):
    write_json(path, sarif_report(new, baselined, suppressed, info,
                                  errors, tool, rules,
                                  tool_version=tool_version))
