"""Human + machine-readable reporting, shared across analyzers.

Every function takes the TOOL name and its RULES catalog so the text a
developer reads names the right command and waiver syntax, while the
structure (what gates, what collapses to counts) is identical across
tools — one report grammar to learn, N analyzers.
"""
from __future__ import annotations

import collections
import json

REPORT_VERSION = 1


def format_finding(f, tag=""):
    tag = f" [{tag}]" if tag else ""
    where = f"{f.path}:{f.line}:{f.col + 1}"
    func = f" in `{f.func}`" if f.func else ""
    return (f"{where}: {f.rule_id} {f.rule} ({f.severity}/"
            f"{f.confidence}){tag}{func}\n    {f.message}")


def human_report(new, baselined, suppressed, info, stale, errors,
                 tool, rules, verbose=False):
    """Report text. `new` findings are always itemized (they gate);
    baselined/suppressed/info collapse to counts unless verbose."""
    out = []
    for f in new:
        out.append(format_finding(f, "NEW"))
    if verbose:
        for f in baselined:
            out.append(format_finding(f, "baselined"))
        for f in suppressed:
            out.append(format_finding(f, "waived"))
        for f in info:
            out.append(format_finding(f, "info"))
    for path, msg in errors:
        out.append(f"{path}: PARSE ERROR — {msg}")
    if stale:
        out.append(f"stale baseline entries ({len(stale)}) — fixed debt; "
                   "shrink the file with --write-baseline:")
        for fp in stale[:20]:
            out.append(f"    {fp}")
        if len(stale) > 20:
            out.append(f"    ... and {len(stale) - 20} more")

    by_rule = collections.Counter(f.rule for f in new + baselined)
    summary = (f"{tool}: {len(new)} new, {len(baselined)} baselined, "
               f"{len(suppressed)} waived inline, {len(info)} info, "
               f"{len(errors)} parse errors")
    if by_rule:
        summary += " | " + ", ".join(
            f"{rules[r].id} {r}: {n}" for r, n in sorted(by_rule.items()))
    out.append(summary)
    if new:
        out.append("FAIL: new findings above — fix them, waive with "
                   f"`# {tool}: ok[rule]` after review, or (for "
                   "accepted debt) refresh the baseline with "
                   "--write-baseline.")
    return "\n".join(out)


def json_report(new, baselined, suppressed, info, stale, errors, rules,
                extra=None):
    payload = {
        "version": REPORT_VERSION,
        "summary": {
            "new": len(new), "baselined": len(baselined),
            "suppressed": len(suppressed), "info": len(info),
            "parse_errors": len(errors), "stale_baseline": len(stale),
        },
        "rules": {slug: {"id": r.id, "severity": r.severity,
                         "manifest": r.manifest, "summary": r.summary}
                  for slug, r in sorted(rules.items())},
        "findings": {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed": [f.to_dict() for f in suppressed],
            "info": [f.to_dict() for f in info],
        },
        "stale_baseline": stale,
        "parse_errors": [{"path": p, "message": m} for p, m in errors],
    }
    if extra:
        payload.update(extra)
    return payload


def write_json(path, payload):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
