#!/usr/bin/env python
"""Telemetry smoke + schema gate (tools/ci_check.sh).

Smoke: a fresh subprocess runs a tiny `Model.fit` with
`hapi.TelemetryCallback` under ``PADDLE_TPU_TELEMETRY_DIR`` and prints
its authoritative snapshots; the parent then proves, from the files
alone (the way a dashboard would):

* the structured event stream exists, with per-step ``train_step``
  events bracketed by ``train_begin``/``train_end``;
* the Prometheus textfile exists and its counters reconcile EXACTLY
  with the child's ``dispatch_stats()`` and ``fault_events()``;
* the per-step scalars file carries one record per batch.

Schema gate: `paddle_tpu.runtime.telemetry.schema()` must equal the
checked-in ``tools/telemetry_schema.json`` — metric/event renames break
dashboards, so they must show up as a reviewed diff of that file.

Usage: python tools/telemetry_smoke.py                (smoke + schema)
       python tools/telemetry_smoke.py --check-schema (schema only)
       python tools/telemetry_smoke.py --emit-schema  (regenerate file)
       python tools/telemetry_smoke.py --child        (internal)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_PATH = os.path.join(REPO, "tools", "telemetry_schema.json")


def _child():
    """Tiny fit with eager warm-up ops so BOTH dispatch paths (per-op
    jit cache and the fused hapi step) feed the exported counters."""
    sys.path.insert(0, REPO)
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.core import dispatch
    from paddle_tpu.runtime.resilience import fault_events, record_fault

    dispatch.set_warmup_count(1)
    dispatch.set_op_sample_every(1)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    # a few plain eager ops: nonzero forward hit/miss traffic to reconcile
    t = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    for _ in range(4):
        paddle.tanh(paddle.matmul(t, t)).sum()
    # a short trace-fusion window: the flush-reason/site attribution
    # counter must carry real traffic to reconcile against
    from paddle_tpu.core import fusion

    fusion.set_fusion(True)
    for _ in range(3):
        float(paddle.tanh(paddle.matmul(t, t)).sum())
    fusion.set_fusion(False)
    record_fault("rollbacks", "telemetry smoke fixture")
    x = rng.rand(64, 4).astype(np.float32)
    y = (x @ rng.rand(4, 1).astype(np.float32)).astype(np.float32)
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    model.fit([x, y], epochs=2, batch_size=16, verbose=0,
              callbacks=[paddle.callbacks.TelemetryCallback(export_every=3)])
    ds = dispatch.dispatch_stats()
    print(json.dumps({
        "forward_hits": ds["forward"]["hits"],
        "forward_misses": ds["forward"]["misses"],
        "fault_events": fault_events(),
        "fusion_flushes": ds["fusion"]["flushes"],
        "fusion_flush_sites": ds["fusion"]["flush_sites"],
        "steps": 8,
    }))


def run_smoke():
    tmp = tempfile.mkdtemp(prefix="telemetry_smoke_")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PADDLE_TPU_TELEMETRY_DIR": tmp,
                "PADDLE_TPU_TELEMETRY": "1"})
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    if p.returncode != 0:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise SystemExit(f"telemetry_smoke: child failed rc={p.returncode}")
    truth = json.loads(p.stdout.strip().splitlines()[-1])

    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import telemetry

    # -- event stream ------------------------------------------------------
    events_path = os.path.join(tmp, "events.jsonl")
    if not os.path.exists(events_path):
        raise SystemExit("telemetry_smoke: no event stream written")
    events = telemetry.read_events(events_path)
    kinds = [e["kind"] for e in events]
    if kinds.count("train_step") != truth["steps"]:
        raise SystemExit(
            f"telemetry_smoke: expected {truth['steps']} train_step events, "
            f"got {kinds.count('train_step')}")
    for needed in ("train_begin", "train_end", "fault"):
        if needed not in kinds:
            raise SystemExit(f"telemetry_smoke: no {needed!r} event emitted")

    # -- prometheus textfile reconciles with the snapshots -----------------
    prom_path = os.path.join(tmp, "metrics.prom")
    if not os.path.exists(prom_path):
        raise SystemExit("telemetry_smoke: no Prometheus textfile written")
    prom = telemetry.parse_prometheus_textfile(prom_path)

    def expect(name, labels, want):
        got = prom.get((name, tuple(sorted(labels))))
        if got != want:
            raise SystemExit(
                f"telemetry_smoke: {name}{dict(labels)} = {got}, but the "
                f"authoritative snapshot says {want} — exported counters "
                "must reconcile exactly")

    expect("paddle_tpu_dispatch_cache_hits_total", [("cache", "forward")],
           truth["forward_hits"])
    expect("paddle_tpu_dispatch_cache_misses_total", [("cache", "forward")],
           truth["forward_misses"])
    for kind, n in truth["fault_events"].items():
        expect("paddle_tpu_fault_events_total", [("fault", kind)], n)
    expect("paddle_tpu_train_steps_total", [], truth["steps"])
    if truth["forward_hits"] <= 0:
        raise SystemExit("telemetry_smoke: the eager workload produced no "
                         "dispatch-cache hits — nothing real reconciled")

    # -- fusion flush-site attribution reconciles with flush totals --------
    n_sites = 0
    for reason, sites in truth["fusion_flush_sites"].items():
        for site, n in sites.items():
            expect("paddle_tpu_fusion_flush_reason_total",
                   [("reason", reason), ("site", site)], n)
            n_sites += 1
        if sum(sites.values()) != truth["fusion_flushes"].get(reason):
            raise SystemExit(
                f"telemetry_smoke: flush_sites[{reason}] sums to "
                f"{sum(sites.values())} but flushes[{reason}] is "
                f"{truth['fusion_flushes'].get(reason)} — the site "
                "table must reconcile exactly with the flush totals")
    if n_sites <= 0:
        raise SystemExit("telemetry_smoke: the fusion window produced no "
                         "attributed flush sites — nothing reconciled")

    # -- scalars -----------------------------------------------------------
    scalars_path = os.path.join(tmp, "scalars.jsonl")
    with open(scalars_path) as f:
        n_scalars = sum(1 for _ in f)
    if n_scalars != truth["steps"]:
        raise SystemExit(f"telemetry_smoke: {n_scalars} scalar records for "
                         f"{truth['steps']} steps")
    print(f"telemetry_smoke: OK ({len(events)} events, "
          f"{len(prom)} prom samples, {n_scalars} scalar records, "
          "counters reconcile)")


def check_schema():
    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import telemetry

    live = telemetry.schema()
    try:
        with open(SCHEMA_PATH) as f:
            frozen = json.load(f)
    except (OSError, ValueError):
        raise SystemExit(
            f"telemetry_smoke: missing/unreadable {SCHEMA_PATH} — "
            "regenerate with `python tools/telemetry_smoke.py "
            "--emit-schema`")
    if live != frozen:
        for field in ("metrics", "events"):
            added = sorted(set(live[field]) - set(frozen.get(field, [])))
            removed = sorted(set(frozen.get(field, [])) - set(live[field]))
            if added:
                print(f"  {field} added:   {', '.join(added)}")
            if removed:
                print(f"  {field} removed: {', '.join(removed)}")
        raise SystemExit(
            "telemetry_smoke: metric/event schema drifted from "
            "tools/telemetry_schema.json. Renames break dashboards; if "
            "deliberate, regenerate with `python tools/telemetry_smoke.py "
            "--emit-schema` and commit the diff.")
    print("telemetry_smoke: schema OK "
          f"({len(live['metrics'])} metrics, {len(live['events'])} events)")


def emit_schema():
    sys.path.insert(0, REPO)
    from paddle_tpu.runtime import telemetry

    with open(SCHEMA_PATH, "w") as f:
        json.dump(telemetry.schema(), f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {SCHEMA_PATH}")


if __name__ == "__main__":
    arg = sys.argv[1] if len(sys.argv) > 1 else ""
    if arg == "--child":
        _child()
    elif arg == "--check-schema":
        check_schema()
    elif arg == "--emit-schema":
        emit_schema()
    else:
        check_schema()
        run_smoke()
