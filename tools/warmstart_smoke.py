#!/usr/bin/env python
"""Warm-start smoke (tools/ci_check.sh): two fresh processes sharing a
persistent compile-cache dir + shape manifest prove the round trip on
CPU in a few seconds.

Pass A (cold): runs a tiny eager workload + fused optimizer step with
``PADDLE_TPU_COMPILE_CACHE_DIR`` set, saves the shape manifest, and
must report fresh XLA compiles (it is doing the work).

Pass B (warm): precompiles the manifest, runs the same workload, and
must report ``disk_cache_hits > 0`` and **zero fresh XLA compiles** —
the warm-start acceptance: every executable came from disk, every
recorded per-op signature was served from the precompiled dispatch
cache.

Usage: python tools/warmstart_smoke.py            (orchestrates both)
       python tools/warmstart_smoke.py --pass a|b (one child pass)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _workload(warm=False):
    """Deterministic eager ops + a fused SGD step; identical across
    passes so every compiled program in B was cached by A. With
    `warm`, the optimizer drains its recorded fused-step signature
    through its owner warmup hook before the first real step."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core import dispatch

    dispatch.set_warmup_count(1)  # compile on first sighting
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    w = paddle.to_tensor(rng.randn(16, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=[w, b])
    prewarmed = opt.warm_start() if warm else 0
    losses = []
    for _ in range(3):
        h = paddle.tanh(paddle.matmul(x, w) + b)
        loss = (h * h).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    return losses, prewarmed


def _run_pass(which):
    sys.path.insert(0, REPO)  # child argv[0] lives in tools/
    from paddle_tpu.core import dispatch
    from paddle_tpu.runtime import warmup

    manifest_path = os.environ["SMOKE_MANIFEST"]
    pre = None
    if which == "b":
        pre = warmup.precompile(manifest_path)
    losses, prewarmed = _workload(warm=which == "b")
    if which == "a":
        warmup.save_manifest(manifest_path)
    comp = dispatch.dispatch_stats()["compile"]
    out = {"losses": losses,
           "fresh_compiles": comp["fresh_compiles"],
           "disk_cache_hits": comp["disk_cache_hits"],
           "backend_compile_s": comp["backend_compile_s"]}
    if which == "b":
        out["precompile"] = pre
        out["prewarmed_programs"] = prewarmed
        out["forward_misses"] = dispatch.dispatch_stats()["forward"]["misses"]
    print(json.dumps(out))


def main():
    tmp = tempfile.mkdtemp(prefix="warmstart_smoke_")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
        "PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S": "0",
        "SMOKE_MANIFEST": os.path.join(tmp, "manifest.json"),
    })

    def run(which):
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pass", which],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
        if p.returncode != 0:
            print(p.stdout)
            print(p.stderr, file=sys.stderr)
            raise SystemExit(f"warmstart_smoke: pass {which} failed "
                             f"(rc={p.returncode})")
        return json.loads(p.stdout.strip().splitlines()[-1])

    a = run("a")
    b = run("b")
    print(f"pass A (cold): {a['fresh_compiles']} fresh compiles "
          f"({a['backend_compile_s']:.2f}s), "
          f"{a['disk_cache_hits']} disk hits")
    print(f"pass B (warm): {b['fresh_compiles']} fresh compiles, "
          f"{b['disk_cache_hits']} disk hits, "
          f"precompiled {b['precompile']['ops_precompiled']} ops + "
          f"{b['prewarmed_programs']} fused-step sigs")
    if b["prewarmed_programs"] < 1:
        raise SystemExit("warmstart_smoke: the optimizer warm_start hook "
                         "drained no recorded fused-step signature")
    if a["fresh_compiles"] == 0:
        raise SystemExit("warmstart_smoke: cold pass compiled nothing — "
                         "the workload no longer exercises the cache")
    if a["losses"] != b["losses"]:
        raise SystemExit("warmstart_smoke: warm pass diverged numerically")
    if b["disk_cache_hits"] == 0:
        raise SystemExit("warmstart_smoke: second pass loaded nothing from "
                         "the persistent compile cache")
    if b["fresh_compiles"] != 0:
        raise SystemExit(
            f"warmstart_smoke: warm pass paid {b['fresh_compiles']} fresh "
            "XLA compiles — the cache key or manifest replay regressed")
    print("warmstart_smoke: OK (zero fresh compiles on the warm pass)")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--pass":
        _run_pass(sys.argv[2])
    else:
        main()
