#!/usr/bin/env python
"""Async-input-pipeline smoke (tools/ci_check.sh): the ISSUE-15
acceptance gates, over fresh subprocesses the way an operator would
run them. Three passes share one compile-cache dir; every pass runs
the SAME seeded workload (an eager trace-fusion window + a small
`Model.fit` over a throttled, data-bound synthetic dataset) under
``PADDLE_TPU_EAGER_FUSION=1`` + ``PADDLE_TPU_TRACE``:

**sync**    — `PADDLE_TPU_DATA_PREFETCH=0`: the serial baseline.
**record**  — prefetch ON (the `DevicePrefetcher` double-buffered
              device staging), saves the warm-start shape manifest.
**replay**  — prefetch ON, precompiles the manifest: the warm second
              process.

Gates (any failure exits nonzero):

* the prefetch loss trajectory is BIT-EXACT vs sync (and vs replay);
* prefetch cuts the measured data-wait seconds by >= 2x on the
  data-bound workload (the `paddle_tpu_data_wait_seconds` histogram
  PR 12 landed so this win would be provable);
* span/metric reconciliation holds in every pass — including the new
  ``io/h2d`` spans vs the `paddle_tpu_h2d_seconds` histogram pair,
  which must be EXERCISED (not skipped) in the prefetch passes;
* fusion flush-site attribution shows ZERO flush sites in the
  prefetch pass that the sync pass didn't have — the staged path may
  never force a flush (device commits bypass dispatch entirely);
* the warm replay pass performs ZERO fresh XLA compiles with the
  prefetcher on (the warm-start contract survives the new thread).

Usage: python tools/data_smoke.py              (orchestrates all)
       python tools/data_smoke.py --pass sync|record|replay
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = 16
BATCH = 16
DELAY_MS = 3.0
# sized so one step's COMPUTE (~14ms on a CPU host) comfortably covers
# one batch's host-side data cost (~4ms: the injected delay + fetch/
# collate overhead) — the regime where double buffering can hide the
# input pipeline entirely, making the >= 2x data-wait gate stable
HIDDEN = 1024


def _workload(warm=False):
    """Seeded, shuffle-free: identical batch values and order in every
    pass, so the loss comparison is exact equality, not tolerance."""
    import time

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.core import dispatch
    from paddle_tpu.runtime import warmup

    dispatch.set_warmup_count(1)
    paddle.seed(0)
    rng = np.random.RandomState(0)

    # the eager fusion window: real flush sites in the attribution
    # table (identical source lines in every pass — the zero-new-sites
    # comparison needs a non-empty baseline to be meaningful)
    t = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    for _ in range(3):
        float(paddle.tanh(paddle.matmul(t, t)).sum())

    n = STEPS * BATCH
    per_item = DELAY_MS * 1e-3 / BATCH
    xs = rng.rand(n, 16).astype(np.float32)
    ys = (xs @ rng.rand(16, 1).astype(np.float32)).astype(np.float32)

    class Throttled(paddle.io.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            time.sleep(per_item)  # the modeled host-side decode cost
            return xs[i], ys[i]

    net = nn.Sequential(nn.Linear(16, HIDDEN), nn.Tanh(),
                        nn.Linear(HIDDEN, HIDDEN), nn.Tanh(),
                        nn.Linear(HIDDEN, 1))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.05, parameters=net.parameters()),
                  nn.MSELoss())
    prewarmed = None
    if warm:
        prewarmed = model.warm_start()
    losses = []

    class _Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            losses.append(logs["loss"])

    model.fit(Throttled(), epochs=1, batch_size=BATCH, shuffle=False,
              verbose=0, callbacks=[_Rec()])
    if not warm:
        warmup.save_manifest(os.environ["DATA_SMOKE_MANIFEST"])
    return losses, prewarmed


def _run_pass(which):
    sys.path.insert(0, REPO)
    from paddle_tpu.core import dispatch
    from paddle_tpu.io import prefetch
    from paddle_tpu.runtime import telemetry, tracing, warmup

    pre = None
    if which == "replay":
        pre = warmup.precompile(os.environ["DATA_SMOKE_MANIFEST"])
    losses, prewarmed = _workload(warm=which == "replay")
    tracing.flush()
    ok, report = tracing.reconcile_with_metrics()
    ds = dispatch.dispatch_stats()

    def _hist(name):
        fam = telemetry.snapshot().get(name) or {}
        series = fam.get("series") or [{}]
        return (float(series[0].get("sum", 0.0)),
                int(series[0].get("count", 0)))

    sites = sorted({site
                    for per_reason in (ds["fusion"]["flush_sites"]
                                       or {}).values()
                    for site in per_reason})
    out = {
        "losses": losses,
        "data_wait_s": _hist("paddle_tpu_data_wait_seconds")[0],
        "h2d": _hist("paddle_tpu_h2d_seconds"),
        "reconcile_ok": ok,
        "reconcile": report,
        "flush_sites": sites,
        "fresh_compiles": ds["compile"]["fresh_compiles"],
        "disk_cache_hits": ds["compile"]["disk_cache_hits"],
        "prefetch": prefetch.prefetch_stats(),
    }
    if pre is not None:
        out["precompile"] = pre
        out["prewarmed"] = prewarmed
    print(json.dumps(out))


def main():
    tmp = tempfile.mkdtemp(prefix="data_smoke_")
    base = dict(os.environ)
    base.update({
        "JAX_PLATFORMS": "cpu",
        "PADDLE_TPU_EAGER_FUSION": "1",
        "PADDLE_TPU_COMPILE_CACHE_DIR": os.path.join(tmp, "cache"),
        "PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S": "0",
        "DATA_SMOKE_MANIFEST": os.path.join(tmp, "manifest.json"),
    })
    base.pop("PADDLE_TPU_SHAPE_MANIFEST", None)

    def run(which, prefetch_on):
        env = dict(base)
        env["PADDLE_TPU_DATA_PREFETCH"] = "1" if prefetch_on else "0"
        env["PADDLE_TPU_TRACE"] = os.path.join(tmp, f"trace_{which}")
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pass", which],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
        if p.returncode != 0:
            print(p.stdout)
            print(p.stderr, file=sys.stderr)
            raise SystemExit(f"data_smoke: pass {which} failed "
                             f"(rc={p.returncode})")
        return json.loads(p.stdout.strip().splitlines()[-1])

    sync = run("sync", prefetch_on=False)
    rec = run("record", prefetch_on=True)
    warm = run("replay", prefetch_on=True)

    problems = []
    if rec["losses"] != sync["losses"]:
        problems.append(
            f"prefetch losses diverged from sync: {rec['losses'][:3]}... "
            f"vs {sync['losses'][:3]}...")
    if warm["losses"] != sync["losses"]:
        problems.append("warm replay losses diverged from sync")
    if not sync["losses"] or len(sync["losses"]) != STEPS:
        problems.append(f"expected {STEPS} steps, got "
                        f"{len(sync['losses'])}")
    # the measurable win: the data-bound workload's wait must collapse
    if rec["data_wait_s"] * 2.0 > sync["data_wait_s"]:
        problems.append(
            f"prefetch did not cut data wait 2x: sync "
            f"{sync['data_wait_s']:.4f}s vs prefetch "
            f"{rec['data_wait_s']:.4f}s")
    for which, r in (("sync", sync), ("record", rec), ("replay", warm)):
        if not r["reconcile_ok"]:
            problems.append(f"{which}: span/metric reconciliation "
                            f"failed: {r['reconcile']}")
    for which, r in (("record", rec), ("replay", warm)):
        h = r["reconcile"].get("h2d") or {}
        if h.get("skipped", True):
            problems.append(f"{which}: the io/h2d <-> "
                            f"paddle_tpu_h2d_seconds pair was never "
                            f"exercised")
        if r["h2d"][1] == 0:
            problems.append(f"{which}: no h2d commits recorded")
        if not r["prefetch"]["batches"]:
            problems.append(f"{which}: the prefetcher served no batches")
        if r["prefetch"]["producer_deaths"] or \
                r["prefetch"]["sync_fallbacks"]:
            problems.append(f"{which}: prefetcher degraded unexpectedly: "
                            f"{r['prefetch']}")
    if not sync["flush_sites"]:
        problems.append("sync pass recorded no fusion flush sites — the "
                        "zero-new-sites comparison lost its baseline")
    new_sites = [s for s in rec["flush_sites"]
                 if s not in sync["flush_sites"]]
    if new_sites:
        problems.append(f"the staged path introduced NEW fusion flush "
                        f"sites: {new_sites}")
    if sync["fresh_compiles"] == 0:
        problems.append("sync pass compiled nothing — the workload no "
                        "longer exercises the compile path")
    if warm["fresh_compiles"] != 0:
        problems.append(f"warm replay paid {warm['fresh_compiles']} fresh "
                        f"XLA compiles with the prefetcher on (want 0)")
    if warm["disk_cache_hits"] <= 0:
        problems.append("warm replay loaded nothing from the persistent "
                        "compile cache")
    if problems:
        for p in problems:
            print(f"data_smoke: FAIL: {p}", file=sys.stderr)
        raise SystemExit(1)
    print(f"data_smoke: OK ({STEPS} steps loss-bit-exact across "
          f"sync/prefetch/warm; data wait "
          f"{sync['data_wait_s']:.3f}s -> {rec['data_wait_s']:.3f}s "
          f"({sync['data_wait_s'] / max(rec['data_wait_s'], 1e-9):.1f}x "
          f"cut), h2d reconciled over {rec['h2d'][1]} commits, "
          f"overlap {rec['prefetch']['overlap_ratio']:.1%}, "
          f"0 new flush sites, warm replay 0 fresh compiles)")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--pass":
        _run_pass(sys.argv[2])
    else:
        main()
