#!/usr/bin/env python
"""Open-loop chaos traffic harness for the serving engine (ISSUE 18).

Drives a `ServingEngine` with OPEN-LOOP arrivals — a Poisson process
(exponential inter-arrivals) plus optional back-to-back bursts — from a
second thread, exactly the regime the scheduler lock contract exists
for. Arrivals do not wait for completions, so an under-provisioned
engine sees unbounded offered load and must SHED (OverloadedError /
``overloaded`` outcome), never wedge: the driver enforces a hard wall
and reports ``wedged`` if the loop fails that contract.

Mixed prompt lengths, token budgets, and per-request deadlines come
from a seeded RNG (deterministic per seed). The report carries the SLO
surface: TTFT p50/p99 and request-latency p50/p99 over ADMITTED
requests, shed rate, goodput tokens/sec, and the max queue depth the
driver (and, optionally, a live ``/statusz`` scraper) observed.
`check_slo()` turns thresholds into violations; the CLI exits 1 on any.

Chaos mode rides the existing FaultInjector sites:

    --chaos delay   ``serve.step`` delay — slow steps; deadlines evict
    --chaos kv      ``serve.kv_alloc`` raise — KV exhaustion degradation

Wedge detection is SERVER-side (ISSUE 20): the drive loop polls the
scheduler's oldest-queued-age (also exported as the
``paddle_tpu_serve_oldest_queued_age_seconds`` gauge and in
``stats()``/(/serving)) and declares ``wedged`` when one request has
sat unserved past ``--wedge-age``; the old client-side hard wall
remains only as a backstop (``wedged_by`` says which tripped).

Record/replay (ISSUE 20): ``--record PATH`` writes the run's offered
schedule + outcomes as ``serve_access``-schema JSONL; ``--replay
PATH`` re-drives those arrival offsets, prompt lengths, budgets, and
deadlines (the loader also accepts a raw engine access log, deriving
offsets from ``t_submit_wall`` deltas). ``--verify-replay`` gates the
run on schedule fidelity.

CLI::

    python tools/loadgen.py --rate 50 --duration 3 --max-queued 16 \\
        --slo-ttft-p99 2.0 --slo-max-shed-rate 0.9

tools/serve_chaos_smoke.py wires this into CI; bench.py's serve_decode
payload reports a short run's SLO keys.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ["build_arrivals", "run_load", "check_slo", "percentile",
           "load_replay_schedule"]


def percentile(values, q):
    """Nearest-rank percentile (q in [0, 100]); None for no samples."""
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def build_arrivals(rate_rps, duration_s, rng, burst_every_s=None,
                   burst_size=0):
    """Open-loop arrival offsets (seconds from start): a Poisson
    process at `rate_rps` over `duration_s`, plus `burst_size`
    back-to-back arrivals every `burst_every_s` (the burst row of the
    failure matrix)."""
    out, t = [], 0.0
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            break
        out.append(t)
    if burst_every_s and burst_size:
        b = burst_every_s
        while b < duration_s:
            out.extend([b] * int(burst_size))
            b += burst_every_s
    out.sort()
    return out


def _pick(rng, value):
    """A scalar stays itself; a sequence is sampled per request."""
    if isinstance(value, (list, tuple)):
        return rng.choice(list(value)) if value else None
    return value


def _scraper(stop, samples, interval_s=0.2):
    """Poll the live /statusz /serving route (third thread — the
    external observer's view of queue depth while the engine is under
    fire)."""
    from paddle_tpu.runtime import diagnostics as _diagnostics

    addr = _diagnostics.statusz_address()
    if addr is None:
        return
    url = f"http://{addr[0]}:{addr[1]}/serving"
    while not stop.wait(interval_s):
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            for eng in doc.get("engines") or []:
                q = (eng.get("queue") or {}).get("depth")
                if q is not None:
                    samples.append(int(q))
        except Exception:  # noqa: BLE001 — a scrape miss is data, not a crash
            continue


def load_replay_schedule(path):
    """Parse a replay schedule from ``--record`` output or a raw engine
    access log (both speak the ``serve_access`` record schema).
    Arrival offsets come from ``arrival_offset_s`` when the record has
    one (loadgen recordings do), else from ``t_submit_wall`` deltas
    against the first record (engine access logs)."""
    from paddle_tpu.inference.journal import iter_jsonl

    recs = [r for r in iter_jsonl(path)
            if r.get("kind", "serve_access") == "serve_access"]
    if not recs:
        raise ValueError(f"no serve_access records in {path}")
    base = None
    out = []
    for r in recs:
        if r.get("arrival_offset_s") is not None:
            off = float(r["arrival_offset_s"])
        else:
            w = float(r.get("t_submit_wall", 0.0))
            if base is None:
                base = w
            off = max(0.0, w - base)
        out.append({"arrival_offset_s": off,
                    "prompt_len": int(r.get("prompt_len") or 1),
                    "max_new_tokens": int(r.get("max_new_tokens") or 1),
                    "deadline_s": r.get("deadline_s")})
    out.sort(key=lambda s: s["arrival_offset_s"])
    return out


def run_load(engine, *, rate_rps, duration_s, prompt_lens=(2, 4, 8),
             new_tokens=(2, 4, 8), deadline_s=None, burst_every_s=None,
             burst_size=0, seed=0, vocab=None, scrape_statusz=False,
             hard_wall_s=None, arrivals=None, wedge_age_s=None):
    """Drive `engine` with open-loop traffic; returns the report dict.

    The submitter runs on a SECOND thread (racing the decode thread's
    plan/evict paths through the scheduler lock); the calling thread
    drives `engine.step()` until the schedule is exhausted and accepted
    work finishes — or a wedge trips (``wedged: True``). Wedge is
    decided by the SERVER's oldest-queued-age (one request unserved
    past `wedge_age_s`); the hard wall is only a backstop.

    `arrivals` replays an explicit schedule (dicts with
    ``arrival_offset_s`` / ``prompt_len`` / ``max_new_tokens`` /
    ``deadline_s``, see `load_replay_schedule`) instead of sampling
    one; token VALUES still come from the seeded RNG."""
    from paddle_tpu.inference import OverloadedError

    rng = random.Random(seed)
    vocab = vocab or getattr(engine.model, "vocab", 32)
    if arrivals is not None:
        specs = sorted(
            ((float(a["arrival_offset_s"]),
              [rng.randrange(1, vocab)
               for _ in range(int(a["prompt_len"]))],
              int(a["max_new_tokens"]),
              a.get("deadline_s"))
             for a in arrivals), key=lambda s: s[0])
    else:
        specs = [(t,
                  [rng.randrange(1, vocab)
                   for _ in range(_pick(rng, prompt_lens))],
                  _pick(rng, new_tokens),
                  _pick(rng, deadline_s))
                 for t in build_arrivals(rate_rps, duration_s, rng,
                                         burst_every_s=burst_every_s,
                                         burst_size=burst_size)]
    ids = set()
    client = []               # per-offered-request client observations
    state = {"shed": 0, "done": False, "errors": 0}
    lock = threading.Lock()

    def submitter():
        t0 = time.perf_counter()
        for t_arr, prompt, n_new, ddl in specs:
            dt = t0 + t_arr - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            t_sub = time.perf_counter()
            obs = {"arrival_offset_s": t_arr, "t_sub": t_sub,
                   "skew_s": t_sub - (t0 + t_arr),
                   "prompt_len": len(prompt), "max_new_tokens": n_new,
                   "deadline_s": ddl, "request_id": None, "shed": False}
            try:
                rid = engine.submit(prompt, max_new_tokens=n_new,
                                    deadline_s=ddl)
                obs["request_id"] = rid
                with lock:
                    ids.add(rid)
                    client.append(obs)
            except OverloadedError as e:
                obs["shed"] = True
                obs["request_id"] = e.request_id
                with lock:
                    state["shed"] += 1
                    client.append(obs)
            except Exception:  # noqa: BLE001 — keep offering load; the
                # report surfaces the count
                with lock:
                    state["errors"] += 1
        state["done"] = True

    th = threading.Thread(target=submitter, name="loadgen-submit",
                          daemon=True)
    stop_scrape = threading.Event()
    scraped = []
    scraper = None
    if scrape_statusz:
        scraper = threading.Thread(target=_scraper,
                                   args=(stop_scrape, scraped),
                                   name="loadgen-scrape", daemon=True)
        scraper.start()
    sched_span = specs[-1][0] if specs else duration_s
    hard = (hard_wall_s if hard_wall_s is not None
            else max(duration_s, sched_span) * 5.0 + 30.0)
    wedge_age = (wedge_age_s if wedge_age_s is not None
                 else max(duration_s, sched_span) * 3.0 + 15.0)
    steps0 = engine.steps
    max_depth = 0
    oldest_max = 0.0
    last_age_check = 0.0
    wedged = False
    wedged_by = None
    t_start = time.perf_counter()
    th.start()
    while not state["done"] or engine.scheduler.has_work():
        now = time.perf_counter()
        if now - t_start > hard:
            wedged, wedged_by = True, "hard_wall"
            break
        if now - last_age_check >= 0.1:
            # the server-published wedge signal: one request sitting
            # unserved this long means the loop is not making progress
            last_age_check = now
            age = engine.scheduler.oldest_queued_age(now=now)
            oldest_max = max(oldest_max, age)
            if age > wedge_age:
                wedged, wedged_by = True, "oldest_queued_age"
                break
        if not engine.step():
            time.sleep(0.001)  # waiting on arrivals, not spinning
        max_depth = max(max_depth, len(engine.scheduler.queue))
    th.join(timeout=10.0)
    stop_scrape.set()
    if scraper is not None:
        scraper.join(timeout=5.0)
    wall = time.perf_counter() - t_start

    fin = [r for r in list(engine.scheduler.finished)
           if r.request_id in ids]
    ev = [r for r in list(engine.scheduler.evicted)
          if r.request_id in ids]
    ttfts = [r.t_first_token - r.t_submit for r in fin
             if r.t_first_token is not None]
    lats = [r.t_done - r.t_submit for r in fin if r.t_done is not None]
    submitted = len(ids) + state["shed"]
    goodput_tokens = sum(len(r.generated) for r in fin)

    # client-measured vs server-recorded TTFT: the client clock starts
    # at the submit() call, the server clock inside ServeRequest — the
    # delta is the submission overhead and must stay tiny
    fin_by = {r.request_id: r for r in fin}
    ev_by = {r.request_id: r for r in ev}
    deltas = []
    records = []
    for o in client:
        r = fin_by.get(o["request_id"]) or ev_by.get(o["request_id"])
        if o["shed"]:
            outcome = "overloaded"
        elif r is None:
            outcome = "in_flight"
        elif r.request_id in fin_by:
            outcome = "completed"
        else:
            outcome = {"cancelled": "cancelled",
                       "queue_timeout": "overloaded"}.get(
                           r.evict_reason, "evicted")
        ttft_srv = client_ttft = None
        if r is not None and r.t_first_token is not None:
            ttft_srv = r.t_first_token - r.t_submit
            client_ttft = r.t_first_token - o["t_sub"]
            deltas.append(client_ttft - ttft_srv)
        records.append({
            "kind": "serve_access",
            "request_id": o["request_id"],
            "arrival_offset_s": round(o["arrival_offset_s"], 6),
            "prompt_len": o["prompt_len"],
            "max_new_tokens": o["max_new_tokens"],
            "deadline_s": o["deadline_s"],
            "outcome": outcome,
            "ttft_s": round(ttft_srv, 6) if ttft_srv is not None else None,
            "client_ttft_s": (round(client_ttft, 6)
                              if client_ttft is not None else None),
        })

    # windowed SLO surface straight off the engine (same numbers the
    # /statusz gauges and /requestz panel publish)
    panel = (engine.slo_panel() if hasattr(engine, "slo_panel") else None)
    w1 = (panel or {}).get("windows", {}).get("1m", {})
    return {
        "offered": len(specs),
        "submitted": submitted,
        "admitted": len(ids),
        "shed": state["shed"],
        "shed_rate": state["shed"] / submitted if submitted else 0.0,
        "completed": len(fin),
        "evicted": len(ev),
        "evicted_by_reason": _count_by(ev),
        "submit_errors": state["errors"],
        "ttft_p50_s": percentile(ttfts, 50),
        "ttft_p99_s": percentile(ttfts, 99),
        "latency_p50_s": percentile(lats, 50),
        "latency_p99_s": percentile(lats, 99),
        "goodput_tokens": goodput_tokens,
        "goodput_tokens_per_sec": goodput_tokens / wall if wall else 0.0,
        "max_queue_depth": max_depth,
        "statusz_samples": len(scraped),
        "statusz_max_queue_depth": max(scraped) if scraped else None,
        "steps": engine.steps - steps0,
        "wall_s": wall,
        "wedged": wedged,
        "wedged_by": wedged_by,
        "oldest_queued_age_max_s": round(oldest_max, 6),
        "arrival_skew_max_s": (round(max(o["skew_s"] for o in client), 6)
                               if client else None),
        "ttft_reconcile_max_delta_s": (round(max(deltas), 6)
                                       if deltas else None),
        "ttft_p50_s_1m": w1.get("ttft_p50_s"),
        "ttft_p99_s_1m": w1.get("ttft_p99_s"),
        "goodput_tokens_per_sec_1m": w1.get("goodput_tokens_per_sec"),
        "shed_rate_1m": w1.get("shed_ratio"),
        "queue_depth_highwater_1m": w1.get("queue_depth_highwater"),
        "windows": (panel or {}).get("windows"),
        "slo": (panel or {}).get("slo"),
        "records": records,
    }


def _count_by(reqs):
    out = {}
    for r in reqs:
        out[r.evict_reason] = out.get(r.evict_reason, 0) + 1
    return out


def check_slo(report, ttft_p99_s=None, min_goodput_tps=None,
              max_shed_rate=None, max_queue_depth=None,
              min_completed=None, ttft_p99_1m_s=None,
              min_goodput_1m_tps=None, max_shed_rate_1m=None):
    """Gate a run's report against SLO thresholds; returns the list of
    violation strings (empty = all gates pass). A wedged run violates
    unconditionally. The ``*_1m`` gates read the engine's rolling
    last-1m window instead of the run-lifetime aggregate."""
    v = []
    if report.get("wedged"):
        v.append("wedged: %s tripped before the queue drained"
                 % (report.get("wedged_by") or "hard wall"))
    if ttft_p99_s is not None:
        got = report.get("ttft_p99_s")
        if got is None:
            v.append("ttft_p99: no admitted request produced a token")
        elif got > ttft_p99_s:
            v.append(f"ttft_p99 {got:.3f}s > {ttft_p99_s:.3f}s")
    if (min_goodput_tps is not None
            and report.get("goodput_tokens_per_sec", 0.0) < min_goodput_tps):
        v.append(f"goodput {report['goodput_tokens_per_sec']:.1f} tok/s"
                 f" < {min_goodput_tps:.1f}")
    if (max_shed_rate is not None
            and report.get("shed_rate", 0.0) > max_shed_rate):
        v.append(f"shed_rate {report['shed_rate']:.3f}"
                 f" > {max_shed_rate:.3f}")
    if (max_queue_depth is not None
            and report.get("max_queue_depth", 0) > max_queue_depth):
        v.append(f"max_queue_depth {report['max_queue_depth']}"
                 f" > {max_queue_depth}")
    if (min_completed is not None
            and report.get("completed", 0) < min_completed):
        v.append(f"completed {report['completed']} < {min_completed}")
    if ttft_p99_1m_s is not None:
        got = report.get("ttft_p99_s_1m")
        if got is None:
            v.append("ttft_p99_1m: no windowed TTFT samples")
        elif got > ttft_p99_1m_s:
            v.append(f"ttft_p99_1m {got:.3f}s > {ttft_p99_1m_s:.3f}s")
    if min_goodput_1m_tps is not None:
        got = report.get("goodput_tokens_per_sec_1m") or 0.0
        if got < min_goodput_1m_tps:
            v.append(f"goodput_1m {got:.1f} tok/s"
                     f" < {min_goodput_1m_tps:.1f}")
    if max_shed_rate_1m is not None:
        got = report.get("shed_rate_1m") or 0.0
        if got > max_shed_rate_1m:
            v.append(f"shed_rate_1m {got:.3f} > {max_shed_rate_1m:.3f}")
    return v


def _build_engine(args):
    from paddle_tpu.inference import ServeConfig, ServingEngine, TinyServeModel

    model = TinyServeModel(seed=args.seed)
    cfg = ServeConfig(max_running=args.max_running,
                      token_budget=args.token_budget,
                      num_blocks=args.num_blocks,
                      block_size=args.block_size,
                      max_queued=args.max_queued,
                      max_queue_wait_s=args.max_queue_wait)
    return ServingEngine(model, cfg, journal=args.journal)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rate", type=float, default=20.0,
                   help="offered arrival rate, requests/sec")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--burst-every", type=float, default=None)
    p.add_argument("--burst-size", type=int, default=0)
    p.add_argument("--prompt-lens", type=int, nargs="+",
                   default=[2, 4, 8])
    p.add_argument("--new-tokens", type=int, nargs="+", default=[2, 4, 8])
    p.add_argument("--deadline", type=float, nargs="*", default=None,
                   help="per-request deadline(s), sampled when several")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-running", type=int, default=4)
    p.add_argument("--token-budget", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-queued", type=int, default=64)
    p.add_argument("--max-queue-wait", type=float, default=None)
    p.add_argument("--journal", default=None)
    p.add_argument("--statusz", action="store_true",
                   help="start /statusz and scrape /serving live")
    p.add_argument("--chaos", choices=["none", "delay", "kv"],
                   default="none")
    p.add_argument("--chaos-arg", type=float, default=None)
    p.add_argument("--slo-ttft-p99", type=float, default=None)
    p.add_argument("--slo-min-goodput", type=float, default=None)
    p.add_argument("--slo-max-shed-rate", type=float, default=None)
    p.add_argument("--slo-max-queue-depth", type=int, default=None)
    p.add_argument("--slo-min-completed", type=int, default=None)
    p.add_argument("--slo-ttft-p99-1m", type=float, default=None,
                   help="gate on the engine's rolling last-1m TTFT p99")
    p.add_argument("--slo-min-goodput-1m", type=float, default=None)
    p.add_argument("--slo-max-shed-rate-1m", type=float, default=None)
    p.add_argument("--wedge-age", type=float, default=None,
                   help="oldest-queued-age (s) that declares a wedge")
    p.add_argument("--record", default=None, metavar="PATH",
                   help="write the offered schedule + outcomes as "
                        "serve_access JSONL (the replay format)")
    p.add_argument("--replay", default=None, metavar="PATH",
                   help="replay the arrival schedule recorded at PATH "
                        "(also accepts a raw engine access log)")
    p.add_argument("--verify-replay", action="store_true",
                   help="with --replay: fail unless the offered "
                        "schedule reproduced the recording exactly")
    args = p.parse_args(argv)

    from paddle_tpu.runtime import diagnostics as _diagnostics
    from paddle_tpu.runtime.resilience import FaultInjector

    engine = _build_engine(args)
    if args.statusz:
        _diagnostics.start_statusz()
    specs = {}
    if args.chaos == "delay":
        specs["serve.step"] = ("delay", args.chaos_arg or 0.05)
    elif args.chaos == "kv":
        # count=0 -> raise on EVERY allocation attempt
        specs["serve.kv_alloc"] = ("raise", int(args.chaos_arg or 0))
    schedule = None
    duration = args.duration
    if args.replay:
        schedule = load_replay_schedule(args.replay)
        duration = (schedule[-1]["arrival_offset_s"] + 0.5
                    if schedule else args.duration)
    kwargs = dict(rate_rps=args.rate, duration_s=duration,
                  prompt_lens=args.prompt_lens,
                  new_tokens=args.new_tokens,
                  deadline_s=args.deadline, burst_every_s=args.burst_every,
                  burst_size=args.burst_size, seed=args.seed,
                  scrape_statusz=args.statusz, arrivals=schedule,
                  wedge_age_s=args.wedge_age)
    if specs:
        with FaultInjector(specs):
            report = run_load(engine, **kwargs)
    else:
        report = run_load(engine, **kwargs)
    violations = check_slo(
        report, ttft_p99_s=args.slo_ttft_p99,
        min_goodput_tps=args.slo_min_goodput,
        max_shed_rate=args.slo_max_shed_rate,
        max_queue_depth=args.slo_max_queue_depth,
        min_completed=args.slo_min_completed,
        ttft_p99_1m_s=args.slo_ttft_p99_1m,
        min_goodput_1m_tps=args.slo_min_goodput_1m,
        max_shed_rate_1m=args.slo_max_shed_rate_1m)
    if args.record:
        with open(args.record, "w") as f:
            for rec in report["records"]:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
    if args.replay:
        want = [(round(s["arrival_offset_s"], 6), s["prompt_len"],
                 s["max_new_tokens"], s["deadline_s"]) for s in schedule]
        got = [(r["arrival_offset_s"], r["prompt_len"],
                r["max_new_tokens"], r["deadline_s"])
               for r in report["records"]]
        fidelity_ok = got == want
        report["replay"] = {
            "source": args.replay,
            "count": len(schedule),
            "fidelity_ok": fidelity_ok,
            "arrival_skew_max_s": report["arrival_skew_max_s"],
        }
        if args.verify_replay and not fidelity_ok:
            violations.append(
                f"replay: offered schedule diverged from {args.replay} "
                f"({len(got)}/{len(want)} offered)")
    report["slo_violations"] = violations
    # per-request records go to --record, not stdout (they scale with
    # offered load; the printed report stays scannable)
    report["records_count"] = len(report.pop("records"))
    print(json.dumps(report, indent=1, sort_keys=True))
    if report.get("wedged"):
        return 2
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
