#!/usr/bin/env python
"""Open-loop chaos traffic harness for the serving engine (ISSUE 18).

Drives a `ServingEngine` with OPEN-LOOP arrivals — a Poisson process
(exponential inter-arrivals) plus optional back-to-back bursts — from a
second thread, exactly the regime the scheduler lock contract exists
for. Arrivals do not wait for completions, so an under-provisioned
engine sees unbounded offered load and must SHED (OverloadedError /
``overloaded`` outcome), never wedge: the driver enforces a hard wall
and reports ``wedged`` if the loop fails that contract.

Mixed prompt lengths, token budgets, and per-request deadlines come
from a seeded RNG (deterministic per seed). The report carries the SLO
surface: TTFT p50/p99 and request-latency p50/p99 over ADMITTED
requests, shed rate, goodput tokens/sec, and the max queue depth the
driver (and, optionally, a live ``/statusz`` scraper) observed.
`check_slo()` turns thresholds into violations; the CLI exits 1 on any.

Chaos mode rides the existing FaultInjector sites:

    --chaos delay   ``serve.step`` delay — slow steps; deadlines evict
    --chaos kv      ``serve.kv_alloc`` raise — KV exhaustion degradation

CLI::

    python tools/loadgen.py --rate 50 --duration 3 --max-queued 16 \\
        --slo-ttft-p99 2.0 --slo-max-shed-rate 0.9

tools/serve_chaos_smoke.py wires this into CI; bench.py's serve_decode
payload reports a short run's SLO keys.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ["build_arrivals", "run_load", "check_slo", "percentile"]


def percentile(values, q):
    """Nearest-rank percentile (q in [0, 100]); None for no samples."""
    if not values:
        return None
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def build_arrivals(rate_rps, duration_s, rng, burst_every_s=None,
                   burst_size=0):
    """Open-loop arrival offsets (seconds from start): a Poisson
    process at `rate_rps` over `duration_s`, plus `burst_size`
    back-to-back arrivals every `burst_every_s` (the burst row of the
    failure matrix)."""
    out, t = [], 0.0
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            break
        out.append(t)
    if burst_every_s and burst_size:
        b = burst_every_s
        while b < duration_s:
            out.extend([b] * int(burst_size))
            b += burst_every_s
    out.sort()
    return out


def _pick(rng, value):
    """A scalar stays itself; a sequence is sampled per request."""
    if isinstance(value, (list, tuple)):
        return rng.choice(list(value)) if value else None
    return value


def _scraper(stop, samples, interval_s=0.2):
    """Poll the live /statusz /serving route (third thread — the
    external observer's view of queue depth while the engine is under
    fire)."""
    from paddle_tpu.runtime import diagnostics as _diagnostics

    addr = _diagnostics.statusz_address()
    if addr is None:
        return
    url = f"http://{addr[0]}:{addr[1]}/serving"
    while not stop.wait(interval_s):
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            for eng in doc.get("engines") or []:
                q = (eng.get("queue") or {}).get("depth")
                if q is not None:
                    samples.append(int(q))
        except Exception:  # noqa: BLE001 — a scrape miss is data, not a crash
            continue


def run_load(engine, *, rate_rps, duration_s, prompt_lens=(2, 4, 8),
             new_tokens=(2, 4, 8), deadline_s=None, burst_every_s=None,
             burst_size=0, seed=0, vocab=None, scrape_statusz=False,
             hard_wall_s=None):
    """Drive `engine` with open-loop traffic; returns the report dict.

    The submitter runs on a SECOND thread (racing the decode thread's
    plan/evict paths through the scheduler lock); the calling thread
    drives `engine.step()` until the schedule is exhausted and accepted
    work finishes — or the hard wall trips (``wedged: True``)."""
    from paddle_tpu.inference import OverloadedError

    rng = random.Random(seed)
    vocab = vocab or getattr(engine.model, "vocab", 32)
    specs = [(t,
              [rng.randrange(1, vocab)
               for _ in range(_pick(rng, prompt_lens))],
              _pick(rng, new_tokens),
              _pick(rng, deadline_s))
             for t in build_arrivals(rate_rps, duration_s, rng,
                                     burst_every_s=burst_every_s,
                                     burst_size=burst_size)]
    ids = set()
    state = {"shed": 0, "done": False, "errors": 0}
    lock = threading.Lock()

    def submitter():
        t0 = time.perf_counter()
        for t_arr, prompt, n_new, ddl in specs:
            dt = t0 + t_arr - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            try:
                rid = engine.submit(prompt, max_new_tokens=n_new,
                                    deadline_s=ddl)
                with lock:
                    ids.add(rid)
            except OverloadedError:
                with lock:
                    state["shed"] += 1
            except Exception:  # noqa: BLE001 — keep offering load; the
                # report surfaces the count
                with lock:
                    state["errors"] += 1
        state["done"] = True

    th = threading.Thread(target=submitter, name="loadgen-submit",
                          daemon=True)
    stop_scrape = threading.Event()
    scraped = []
    scraper = None
    if scrape_statusz:
        scraper = threading.Thread(target=_scraper,
                                   args=(stop_scrape, scraped),
                                   name="loadgen-scrape", daemon=True)
        scraper.start()
    hard = (hard_wall_s if hard_wall_s is not None
            else duration_s * 5.0 + 30.0)
    steps0 = engine.steps
    max_depth = 0
    wedged = False
    t_start = time.perf_counter()
    th.start()
    while not state["done"] or engine.scheduler.has_work():
        if time.perf_counter() - t_start > hard:
            wedged = True
            break
        if not engine.step():
            time.sleep(0.001)  # waiting on arrivals, not spinning
        max_depth = max(max_depth, len(engine.scheduler.queue))
    th.join(timeout=10.0)
    stop_scrape.set()
    if scraper is not None:
        scraper.join(timeout=5.0)
    wall = time.perf_counter() - t_start

    fin = [r for r in list(engine.scheduler.finished)
           if r.request_id in ids]
    ev = [r for r in list(engine.scheduler.evicted)
          if r.request_id in ids]
    ttfts = [r.t_first_token - r.t_submit for r in fin
             if r.t_first_token is not None]
    lats = [r.t_done - r.t_submit for r in fin if r.t_done is not None]
    submitted = len(ids) + state["shed"]
    goodput_tokens = sum(len(r.generated) for r in fin)
    return {
        "offered": len(specs),
        "submitted": submitted,
        "admitted": len(ids),
        "shed": state["shed"],
        "shed_rate": state["shed"] / submitted if submitted else 0.0,
        "completed": len(fin),
        "evicted": len(ev),
        "evicted_by_reason": _count_by(ev),
        "submit_errors": state["errors"],
        "ttft_p50_s": percentile(ttfts, 50),
        "ttft_p99_s": percentile(ttfts, 99),
        "latency_p50_s": percentile(lats, 50),
        "latency_p99_s": percentile(lats, 99),
        "goodput_tokens": goodput_tokens,
        "goodput_tokens_per_sec": goodput_tokens / wall if wall else 0.0,
        "max_queue_depth": max_depth,
        "statusz_samples": len(scraped),
        "statusz_max_queue_depth": max(scraped) if scraped else None,
        "steps": engine.steps - steps0,
        "wall_s": wall,
        "wedged": wedged,
    }


def _count_by(reqs):
    out = {}
    for r in reqs:
        out[r.evict_reason] = out.get(r.evict_reason, 0) + 1
    return out


def check_slo(report, ttft_p99_s=None, min_goodput_tps=None,
              max_shed_rate=None, max_queue_depth=None,
              min_completed=None):
    """Gate a run's report against SLO thresholds; returns the list of
    violation strings (empty = all gates pass). A wedged run violates
    unconditionally."""
    v = []
    if report.get("wedged"):
        v.append("wedged: hard wall tripped before the queue drained")
    if ttft_p99_s is not None:
        got = report.get("ttft_p99_s")
        if got is None:
            v.append("ttft_p99: no admitted request produced a token")
        elif got > ttft_p99_s:
            v.append(f"ttft_p99 {got:.3f}s > {ttft_p99_s:.3f}s")
    if (min_goodput_tps is not None
            and report.get("goodput_tokens_per_sec", 0.0) < min_goodput_tps):
        v.append(f"goodput {report['goodput_tokens_per_sec']:.1f} tok/s"
                 f" < {min_goodput_tps:.1f}")
    if (max_shed_rate is not None
            and report.get("shed_rate", 0.0) > max_shed_rate):
        v.append(f"shed_rate {report['shed_rate']:.3f}"
                 f" > {max_shed_rate:.3f}")
    if (max_queue_depth is not None
            and report.get("max_queue_depth", 0) > max_queue_depth):
        v.append(f"max_queue_depth {report['max_queue_depth']}"
                 f" > {max_queue_depth}")
    if (min_completed is not None
            and report.get("completed", 0) < min_completed):
        v.append(f"completed {report['completed']} < {min_completed}")
    return v


def _build_engine(args):
    from paddle_tpu.inference import ServeConfig, ServingEngine, TinyServeModel

    model = TinyServeModel(seed=args.seed)
    cfg = ServeConfig(max_running=args.max_running,
                      token_budget=args.token_budget,
                      num_blocks=args.num_blocks,
                      block_size=args.block_size,
                      max_queued=args.max_queued,
                      max_queue_wait_s=args.max_queue_wait)
    return ServingEngine(model, cfg, journal=args.journal)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rate", type=float, default=20.0,
                   help="offered arrival rate, requests/sec")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--burst-every", type=float, default=None)
    p.add_argument("--burst-size", type=int, default=0)
    p.add_argument("--prompt-lens", type=int, nargs="+",
                   default=[2, 4, 8])
    p.add_argument("--new-tokens", type=int, nargs="+", default=[2, 4, 8])
    p.add_argument("--deadline", type=float, nargs="*", default=None,
                   help="per-request deadline(s), sampled when several")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-running", type=int, default=4)
    p.add_argument("--token-budget", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-queued", type=int, default=64)
    p.add_argument("--max-queue-wait", type=float, default=None)
    p.add_argument("--journal", default=None)
    p.add_argument("--statusz", action="store_true",
                   help="start /statusz and scrape /serving live")
    p.add_argument("--chaos", choices=["none", "delay", "kv"],
                   default="none")
    p.add_argument("--chaos-arg", type=float, default=None)
    p.add_argument("--slo-ttft-p99", type=float, default=None)
    p.add_argument("--slo-min-goodput", type=float, default=None)
    p.add_argument("--slo-max-shed-rate", type=float, default=None)
    p.add_argument("--slo-max-queue-depth", type=int, default=None)
    p.add_argument("--slo-min-completed", type=int, default=None)
    args = p.parse_args(argv)

    from paddle_tpu.runtime import diagnostics as _diagnostics
    from paddle_tpu.runtime.resilience import FaultInjector

    engine = _build_engine(args)
    if args.statusz:
        _diagnostics.start_statusz()
    specs = {}
    if args.chaos == "delay":
        specs["serve.step"] = ("delay", args.chaos_arg or 0.05)
    elif args.chaos == "kv":
        # count=0 -> raise on EVERY allocation attempt
        specs["serve.kv_alloc"] = ("raise", int(args.chaos_arg or 0))
    kwargs = dict(rate_rps=args.rate, duration_s=args.duration,
                  prompt_lens=args.prompt_lens,
                  new_tokens=args.new_tokens,
                  deadline_s=args.deadline, burst_every_s=args.burst_every,
                  burst_size=args.burst_size, seed=args.seed,
                  scrape_statusz=args.statusz)
    if specs:
        with FaultInjector(specs):
            report = run_load(engine, **kwargs)
    else:
        report = run_load(engine, **kwargs)
    violations = check_slo(
        report, ttft_p99_s=args.slo_ttft_p99,
        min_goodput_tps=args.slo_min_goodput,
        max_shed_rate=args.slo_max_shed_rate,
        max_queue_depth=args.slo_max_queue_depth,
        min_completed=args.slo_min_completed)
    report["slo_violations"] = violations
    print(json.dumps(report, indent=1, sort_keys=True))
    if report.get("wedged"):
        return 2
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
