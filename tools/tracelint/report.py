"""Human + machine-readable reporting for tracelint findings.

The report grammar is the shared tools/staticlib/report.py core; this
module binds the tool name (command/waiver syntax in the text) and the
manifest section tracelint's JSON report carries.
"""
from __future__ import annotations

from ..staticlib.report import (  # noqa: F401 — re-exported API
    REPORT_VERSION, format_finding, write_json,
)
from ..staticlib.report import human_report as _human_report
from ..staticlib.report import json_report as _json_report
from .rules import RULES


def human_report(new, baselined, suppressed, info, stale, errors,
                 verbose=False):
    return _human_report(new, baselined, suppressed, info, stale, errors,
                         tool="tracelint", rules=RULES, verbose=verbose)


def json_report(new, baselined, suppressed, info, stale, errors,
                manifest_entries=None):
    return _json_report(
        new, baselined, suppressed, info, stale, errors, rules=RULES,
        extra={"manifest": (
            {"|".join(map(str, k)): v
             for k, v in sorted(manifest_entries.items())}
            if manifest_entries is not None else None)})
