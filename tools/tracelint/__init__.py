"""tracelint — AST jit-safety analysis for the paddle_tpu eager
dispatch layer.

PR 1's dispatch cache discovers trace hazards at RUNTIME: a closure
over a live array silently bypasses the cache on every call, and each
genuinely unjittable op pays one failed `jax.jit` compile before the
blacklist learns it.  tracelint moves those discoveries to lint time:
a stdlib-`ast` pass walks every op body reachable through
`core.autograd.apply` / `core.dispatch.run_op`, classifies
trace-hygiene hazards (rules.py), and emits

  * a human report (file:line, gate for CI via tools/ci_check.sh),
  * a machine-readable JSON report (--json),
  * the static unjittable manifest
    `paddle_tpu/core/_unjittable_manifest.py` (--emit-manifest) that
    dispatch preloads at import so proven-unsafe ops never pay a
    failed-compile probe.

The analysis harness (scope/taint machinery, fingerprint baseline,
inline waivers, report grammar) is the shared `tools/staticlib/` core;
this package carries only the jit-specific rule catalog, visitors and
the unjittable-manifest emitter. threadlint (docs/THREADLINT.md) is
the same harness bound to a concurrency catalog.

Usage:
    python -m tools.tracelint paddle_tpu
    python -m tools.tracelint paddle_tpu --emit-manifest
    python -m tools.tracelint paddle_tpu --json /tmp/tracelint.json
    python -m tools.tracelint paddle_tpu --write-baseline

See docs/TRACELINT.md for the rule catalog and workflows.
"""
from .analyzer import Finding, analyze_file, analyze_paths
from .baseline import load_baseline, partition, write_baseline
from .manifest import manifest_entries, manifest_key_path, write_manifest
from .rules import RULES

__all__ = [
    "Finding", "analyze_file", "analyze_paths", "load_baseline",
    "partition", "write_baseline", "manifest_entries", "manifest_key_path",
    "write_manifest", "RULES", "main",
]

__version__ = "1.0"


def main(argv=None):
    from .__main__ import main as _main
    return _main(argv)
