"""CLI: python -m tools.tracelint <roots...> [options].

Exit codes: 0 clean (or baselined-only), 1 new findings or parse
errors, 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from .analyzer import analyze_paths
from .baseline import DEFAULT_BASELINE, load_baseline, partition, \
    write_baseline
from .manifest import MANIFEST_BASENAME, manifest_entries, write_manifest
from .report import human_report, json_report, write_json


def _default_manifest_path(roots):
    """paddle_tpu/core/_unjittable_manifest.py under the analyzed
    package when one of the roots IS the package; else error."""
    for r in roots:
        cand = os.path.join(r, "core", MANIFEST_BASENAME)
        if os.path.isdir(os.path.join(r, "core")):
            return cand
    return None


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m tools.tracelint",
        description="AST jit-safety analyzer for the paddle_tpu eager "
                    "dispatch layer (see docs/TRACELINT.md)")
    p.add_argument("roots", nargs="+",
                   help="package dirs or files to analyze (paddle_tpu)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help=f"baseline file (default {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new (ignore baseline)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "and exit 0")
    p.add_argument("--json", metavar="PATH",
                   help="also write the machine-readable report here")
    p.add_argument("--sarif", metavar="PATH",
                   help="also write a SARIF 2.1.0 report here (CI "
                        "code-scanning annotations)")
    p.add_argument("--emit-manifest", action="store_true",
                   help="regenerate the static unjittable manifest")
    p.add_argument("--manifest-path", default=None,
                   help="manifest output (default: <root>/core/"
                        f"{MANIFEST_BASENAME})")
    p.add_argument("--no-audit-suspend", action="store_true",
                   help="skip the whole-program suspend() audit rule")
    p.add_argument("--check-manifest", action="store_true",
                   help="fail if the checked-in manifest differs from "
                        "what the analysis would generate")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="itemize baselined/waived/info findings too")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    for r in args.roots:
        if not os.path.exists(r):
            print(f"tracelint: no such path: {r}", file=sys.stderr)
            return 2

    findings, errors = analyze_paths(
        args.roots, audit_suspend=not args.no_audit_suspend)

    if args.write_baseline:
        if errors:
            # a baseline written while files are unparseable silently
            # drops their debt; the next clean run would gate on it
            for p, m in errors:
                print(f"{p}: PARSE ERROR — {m}", file=sys.stderr)
            print("tracelint: refusing to write a baseline while files "
                  "fail to parse", file=sys.stderr)
            return 1
        counts = write_baseline(args.baseline, findings)
        print(f"tracelint: baseline written to {args.baseline} "
              f"({sum(counts.values())} findings, "
              f"{len(counts)} distinct fingerprints)")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined, suppressed, info, stale = partition(findings, baseline)

    entries = manifest_entries(findings)
    manifest_changed = False
    mpath = args.manifest_path or _default_manifest_path(args.roots)
    if args.emit_manifest or args.check_manifest:
        if mpath is None:
            print("tracelint: cannot infer --manifest-path from roots",
                  file=sys.stderr)
            return 2
        if args.emit_manifest:
            entries, manifest_changed = write_manifest(findings, mpath)
            print(f"tracelint: manifest {'rewritten' if manifest_changed else 'unchanged'}: "
                  f"{mpath} ({len(entries)} entries)")
        else:  # --check-manifest: compare without writing
            from .manifest import render_manifest
            want = render_manifest(entries)
            have = ""
            if os.path.exists(mpath):
                with open(mpath, "r", encoding="utf-8") as f:
                    have = f.read()
            if want != have:
                print(f"tracelint: manifest STALE: {mpath} — regenerate "
                      "with --emit-manifest", file=sys.stderr)
                errors = errors + [(mpath, "stale manifest")]

    print(human_report(new, baselined, suppressed, info, stale, errors,
                       verbose=args.verbose))
    if args.json:
        write_json(args.json, json_report(new, baselined, suppressed, info,
                                          stale, errors, entries))
    if args.sarif:
        from ..staticlib.report import write_sarif
        from .rules import RULES

        write_sarif(args.sarif, new, baselined, suppressed, info, errors,
                    tool="tracelint", rules=RULES)
    return 1 if (new or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
