"""AST jit-safety analysis over the paddle_tpu op surface.

What counts as an "op body": any function object that can reach
`jax.jit` through the eager dispatch layer —

  * the first argument of a call to ``apply(...)`` / ``_apply(...)``
    (core.autograd.apply) or ``run_op(...)`` when it is a lambda or a
    name that resolves to a def/lambda in lexical scope;
  * any function marked ``@non_jittable`` (decorator or direct
    ``non_jittable(fn)`` call) — analyzed both for hazards and for
    staleness of the marking.

Within an op body the analysis runs a conservative name-level taint
pass: positional parameters without defaults are assumed traced
(arrays); parameters with defaults and closure statics are assumed
static.  Shape/dtype/ndim reads, ``len()``, ``isinstance()`` etc.
sanitize taint (they are static under trace).  Hazard visitors then
classify findings per rules.py; a finding is *definite* (manifest
grade) only when the hazard holds regardless of which argument is
traced (``.numpy()``, ``time.time()``, host randomness) or when it
touches a name the body itself treats as an array (passed to
jnp/lax/jax calls).

The pass is intentionally file-local and approximate: it must never
import the code it inspects (analysis of a broken tree is exactly when
lint is most useful), and false positives are absorbed by the checked
baseline rather than by weakening detection.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re

from .rules import RULES

__all__ = ["Finding", "analyze_file", "analyze_paths", "iter_py_files"]


# ---------------------------------------------------------------------------
# model

@dataclasses.dataclass
class Finding:
    rule: str           # rules.py slug
    path: str           # posix path relative to the analysis root's parent
    line: int
    col: int
    func: str           # dotted qualname of the op body ("" for module)
    func_name: str      # runtime co_name ("<lambda>" for lambdas)
    func_line: int      # runtime co_firstlineno of the op body
    message: str
    symbol: str         # short stable token for fingerprinting
    severity: str
    confidence: str     # "definite" | "possible"
    context: str        # "op-body" | "non-jittable" | "trace-site"
    suppressed: bool = False

    @property
    def rule_id(self):
        return RULES[self.rule].id

    def fingerprint(self):
        """Line-number-free identity: survives unrelated edits above the
        finding, so the baseline doesn't churn with the file."""
        return f"{self.rule}|{self.path}|{self.func}|{self.symbol}"

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["rule_id"] = self.rule_id
        d["fingerprint"] = self.fingerprint()
        return d


# ---------------------------------------------------------------------------
# shared AST helpers

DISPATCH_NAMES = {"apply", "_apply", "run_op"}
NON_JITTABLE_NAMES = {"non_jittable"}

# attribute reads that are static under a jax trace — they sanitize taint
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "name",
                "itemsize", "nbytes"}
# calls whose result is host-static regardless of argument taint
# (shape/dtype queries are resolved at trace time, not run time)
SANITIZER_CALLS = {"len", "isinstance", "issubclass", "type", "id",
                   "repr", "str", "format", "hasattr", "callable",
                   "result_type", "issubdtype", "can_cast",
                   "promote_types", "iscomplexobj", "isrealobj",
                   "ndim", "shape", "finfo", "iinfo"}
# scalar coercions: hazardous only on a traced operand
COERCIONS = {"float", "int", "bool", "complex"}
HOST_METHODS = {"numpy", "item", "tolist"}
NP_HOST_FUNCS = {"asarray", "array", "asanyarray", "ascontiguousarray",
                 "frombuffer", "copyto", "save", "savez"}
IMPURE_MODULE_HEADS = {"time", "random", "secrets", "uuid", "datetime"}
MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop", "clear",
                    "update", "setdefault", "add", "discard", "popitem",
                    "write", "writelines", "sort", "reverse"}
ARRAY_METHODS = {"astype", "reshape", "sum", "mean", "transpose", "ravel",
                 "squeeze", "flatten", "min", "max", "at", "dot", "take",
                 "cumsum", "prod", "conj", "real", "imag", "round", "clip",
                 "numpy", "item", "tolist"}
ARRAY_CALL_HEADS = {"jnp", "jax", "lax", "_jnp", "jsp"}
MODULE_HEADS = ARRAY_CALL_HEADS | {"np", "numpy", "math", "os", "sys",
                                   "warnings", "collections", "itertools"}
KEYISH_NAME = re.compile(r"(^|_)(key|keys|rng|rngs|seed|prng)(_|$)|"
                         r"(^|_)(rand|noise)(_|$)")
ARRAY_PRODUCER_FUNCS = {"Tensor", "to_tensor", "asarray", "next_key",
                        "PRNGKey", "key", "split", "fold_in", "randn",
                        "rand", "uniform", "normal", "zeros", "ones",
                        "arange", "full", "empty"}

# whole-program trace entry points for the suspend audit
TRACE_ENTRY_DOTTED = {
    ("jax", "jit"), ("jax", "value_and_grad"), ("jax", "make_jaxpr"),
    ("jax", "eval_shape"), ("jax", "linearize"),
    ("lax", "cond"), ("lax", "switch"), ("lax", "while_loop"),
    ("jax", "lax", "cond"), ("jax", "lax", "switch"),
    ("jax", "lax", "while_loop"),
    ("jexport", "export"), ("export", "export"),
}
TRACE_ENTRY_BARE = {"shard_map"}


def dotted(node):
    """('jax','jit') for jax.jit, ('x',) for x; None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def runtime_first_line(node):
    """co_firstlineno of the code object this def/lambda compiles to:
    for decorated defs that is the FIRST DECORATOR line, not the `def`
    line (CPython 3.8+ ast puts .lineno on the def)."""
    decs = getattr(node, "decorator_list", None)
    if decs:
        return min([d.lineno for d in decs] + [node.lineno])
    return node.lineno


def func_params(node):
    """(all param names, names assumed TRACED). Params with defaults are
    assumed static — the codebase idiom rides statics in via defaults
    (`lambda x, axis=axis: ...`) and arrays positionally."""
    a = node.args
    names, traced = [], set()
    pos = list(a.posonlyargs) + list(a.args)
    n_def = len(a.defaults)
    for i, p in enumerate(pos):
        names.append(p.arg)
        if i < len(pos) - n_def:
            traced.add(p.arg)
    if a.vararg:
        names.append(a.vararg.arg)
        traced.add(a.vararg.arg)
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        names.append(p.arg)
        if d is None:
            traced.add(p.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names, traced


class _ScopeIndex:
    """Parent links + lexical scope chains for one module AST."""

    def __init__(self, tree):
        self.parent = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.tree = tree

    def scope_chain(self, node):
        """Enclosing FunctionDef/AsyncFunctionDef/Lambda/ClassDef nodes,
        innermost first (the node itself excluded)."""
        out = []
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                out.append(cur)
            cur = self.parent.get(cur)
        return out

    def qualname(self, node):
        parts = []
        for s in [node] + self.scope_chain(node):
            if isinstance(s, ast.Lambda):
                parts.append("<lambda>")
            else:
                parts.append(s.name)
        return ".".join(reversed(parts))

    def resolve_function(self, name, from_node):
        """Find the def/lambda a bare name refers to at `from_node`,
        searching enclosing function scopes innermost-out, then module
        level. Returns the AST node or None."""
        scopes = [s for s in self.scope_chain(from_node)
                  if not isinstance(s, ast.ClassDef)]
        scopes.append(self.tree)
        for scope in scopes:
            body = scope.body if not isinstance(scope, ast.Lambda) else []
            hit = None
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == name:
                    hit = stmt
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name \
                                and isinstance(stmt.value, ast.Lambda):
                            hit = stmt.value
            if hit is not None:
                return hit
        return None


# ---------------------------------------------------------------------------
# per-op-body hazard analysis

class _OpBodyChecker:
    def __init__(self, fnode, scopes, relpath, lines, findings, context):
        self.fnode = fnode
        self.scopes = scopes
        self.relpath = relpath
        self.lines = lines
        self.findings = findings
        self.context = context
        self.qual = scopes.qualname(fnode)
        self.func_name = (fnode.name
                          if isinstance(fnode, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                          else "<lambda>")
        self.func_line = runtime_first_line(fnode)
        self.n_found = 0

        self.params, self.tainted = func_params(fnode)
        self.vararg = fnode.args.vararg.arg if fnode.args.vararg else None
        self.locals = set(self.params)
        self._collect_locals()
        self.array_evidence = self._collect_array_evidence()
        self._propagate_taint()

    # -- scope bookkeeping --------------------------------------------------
    def _body_nodes(self):
        if isinstance(self.fnode, ast.Lambda):
            yield from ast.walk(self.fnode.body)
        else:
            for stmt in self.fnode.body:
                yield from ast.walk(stmt)

    def _collect_locals(self):
        for n in self._body_nodes():
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.locals.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.locals.add(n.name)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        self.locals.add(t.id)

    def _collect_array_evidence(self):
        """Names the body itself treats as arrays: fed to jnp/lax/jax
        calls or used with array methods."""
        ev = set()
        for n in self._body_nodes():
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d[0] in ARRAY_CALL_HEADS:
                    for a in list(n.args) + [kw.value for kw in n.keywords]:
                        for nm in ast.walk(a):
                            if isinstance(nm, ast.Name):
                                ev.add(nm.id)
            if isinstance(n, ast.Attribute) and n.attr in ARRAY_METHODS \
                    and isinstance(n.value, ast.Name):
                ev.add(n.value.id)
            if isinstance(n, ast.BinOp):
                for side in (n.left, n.right):
                    if isinstance(side, ast.Name):
                        ev.add(side.id)
        return ev

    def _propagate_taint(self):
        """Name-level forward taint, iterated to a small fixpoint."""
        for _ in range(3):
            changed = False
            for n in self._body_nodes():
                tgts = None
                if isinstance(n, ast.Assign):
                    tgts, val = n.targets, n.value
                elif isinstance(n, ast.AugAssign):
                    tgts, val = [n.target], n.value
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    tgts, val = [n.target], n.value
                elif isinstance(n, ast.NamedExpr):
                    tgts, val = [n.target], n.value
                if not tgts or not self.expr_tainted(val):
                    continue
                for t in tgts:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) \
                                and nm.id not in self.tainted:
                            self.tainted.add(nm.id)
                            changed = True
            if not changed:
                break

    # -- taint query --------------------------------------------------------
    def expr_tainted(self, node):
        if node is None:
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and (d[-1] in SANITIZER_CALLS or d[-1] in COERCIONS
                      or d[-1] in HOST_METHODS):
                return False  # result is host-static (the call itself
                #               may be a hazard, reported separately)
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if self.expr_tainted(a):
                    return True
            # method call: the receiver's taint flows to the result
            # (x.astype(...) is as traced as x)
            if isinstance(node.func, ast.Attribute):
                return self.expr_tainted(node.func.value)
            return False
        if isinstance(node, ast.Name):
            # the *args TUPLE is a host object (its truthiness/len are
            # trace-static); only its ELEMENTS carry taint
            if node.id == self.vararg:
                return False
            return node.id in self.tainted
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == self.vararg:
            return True
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # `x is None` is an identity test on the HOST object — a
            # tracer is never None, so the branch is trace-static
            return False
        for child in ast.iter_child_nodes(node):
            if self.expr_tainted(child):
                return True
        return False

    def _taint_names(self, node):
        return sorted({n.id for n in ast.walk(node)
                       if isinstance(n, ast.Name) and n.id in self.tainted})

    # -- reporting ----------------------------------------------------------
    def report(self, rule, node, message, symbol, confidence):
        sev = RULES[rule].severity
        self.findings.append(Finding(
            rule=rule, path=self.relpath, line=node.lineno,
            col=node.col_offset, func=self.qual, func_name=self.func_name,
            func_line=self.func_line, message=message, symbol=symbol,
            severity=sev, confidence=confidence, context=self.context))
        self.n_found += 1

    # -- the checks ---------------------------------------------------------
    def run(self):
        self._check_declared_state()
        for n in self._body_nodes():
            if isinstance(n, ast.Call):
                self._check_call(n)
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                self._check_store(n)
            elif isinstance(n, ast.If):
                self._check_branch(n, n.test, "if")
            elif isinstance(n, ast.While):
                self._check_branch(n, n.test, "while")
            elif isinstance(n, ast.IfExp):
                self._check_branch(n, n.test, "ternary")
            elif isinstance(n, ast.Assert):
                self._check_branch(n, n.test, "assert")
            elif isinstance(n, ast.For):
                if self.expr_tainted(n.iter):
                    self.report(
                        "data-dependent-control-flow", n,
                        "for-loop iterates over a traced value "
                        f"({', '.join(self._taint_names(n.iter))}) — the "
                        "trace unrolls per element or fails on dynamic "
                        "length", "for:" + ",".join(self._taint_names(n.iter)),
                        "possible")
        self._check_closure_capture()
        return self.n_found

    def _check_declared_state(self):
        for n in self._body_nodes():
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(n, ast.Global) else "nonlocal"
                self.report(
                    "state-mutation", n,
                    f"`{kind} {', '.join(n.names)}` inside an op body — "
                    "the rebind happens once at trace time, then never "
                    "again in the compiled program",
                    f"{kind}:{','.join(n.names)}", "definite")

    def _check_call(self, n):
        d = dotted(n.func)
        # .numpy()/.item()/.tolist() — host sync no matter which operand
        if isinstance(n.func, ast.Attribute) and n.func.attr in HOST_METHODS:
            base = n.func.value
            base_d = dotted(base)
            if base_d and base_d[0] in IMPURE_MODULE_HEADS:
                pass  # e.g. datetime.date.today().tolist() — TL004 below
            else:
                conf = ("definite"
                        if self.expr_tainted(base)
                        or (isinstance(base, ast.Name)
                            and base.id in self.array_evidence)
                        else "possible")
                self.report(
                    "host-materialize", n,
                    f".{n.func.attr}() forces a host transfer inside a "
                    "potentially-traced op body (fails on tracers, "
                    "de-optimizes on arrays)",
                    f".{n.func.attr}", conf)
                return
        # float(x)/int(x)/bool(x) on traced values
        if d and len(d) == 1 and d[0] in COERCIONS and n.args:
            if self.expr_tainted(n.args[0]):
                names = self._taint_names(n.args[0])
                in_ev = any(nm in self.array_evidence for nm in names)
                self.report(
                    "host-materialize", n,
                    f"{d[0]}() on a traced value "
                    f"({', '.join(names)}) raises "
                    "ConcretizationTypeError under trace",
                    f"{d[0]}:{','.join(names)}",
                    "definite" if in_ev else "possible")
                return
        # np.asarray & friends on traced values
        if d and len(d) >= 2 and d[0] in ("np", "numpy") \
                and d[-1] in NP_HOST_FUNCS:
            if len(d) >= 2 and d[1] == "random":
                pass  # np.random.* handled as impurity below
            elif any(self.expr_tainted(a) for a in n.args):
                names = [nm for a in n.args for nm in self._taint_names(a)]
                self.report(
                    "host-materialize", n,
                    f"{'.'.join(d)} materializes a traced value "
                    f"({', '.join(names)}) on host",
                    ".".join(d), "definite")
                return
        # wall clock / host randomness
        if d and d[0] in IMPURE_MODULE_HEADS and len(d) >= 2:
            self.report(
                "impure-call", n,
                f"{'.'.join(d)}() inside an op body — the value is "
                "frozen at trace time and replayed by every cached call",
                ".".join(d), "definite")
            return
        if d and len(d) >= 3 and d[0] in ("np", "numpy") and d[1] == "random":
            self.report(
                "impure-call", n,
                f"{'.'.join(d)}() — numpy host randomness freezes into "
                "the compiled program; thread a jax PRNG key instead",
                ".".join(d), "definite")
            return
        if d and d[0] == "os" and d[-1] == "urandom":
            self.report("impure-call", n, "os.urandom inside an op body",
                        "os.urandom", "definite")
            return
        # mutating method on a free (captured) name — but not on a
        # module (jnp.sort is numpy-API sort, not list mutation)
        if isinstance(n.func, ast.Attribute) \
                and n.func.attr in MUTATING_METHODS \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id not in self.locals \
                and n.func.value.id not in MODULE_HEADS:
            self.report(
                "state-mutation", n,
                f"`{n.func.value.id}.{n.func.attr}(...)` mutates captured "
                "state — runs once at trace time, never per compiled call",
                f"{n.func.value.id}.{n.func.attr}", "possible")

    def _check_store(self, n):
        tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
        for t in tgts:
            root = t
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if root is t:
                continue  # plain name store: local, fine
            if isinstance(root, ast.Name) and root.id not in self.locals:
                kind = ("attribute"
                        if isinstance(t, ast.Attribute) else "subscript")
                self.report(
                    "state-mutation", n,
                    f"{kind} store on captured `{root.id}` inside an op "
                    "body — the write happens at trace time only",
                    f"store:{root.id}", "definite")

    def _check_branch(self, node, test, kind):
        if not self.expr_tainted(test):
            return
        names = self._taint_names(test)
        in_ev = any(nm in self.array_evidence for nm in names)
        self.report(
            "data-dependent-control-flow", node,
            f"`{kind}` on a traced value ({', '.join(names)}) — "
            "TracerBoolConversionError under trace; use jnp.where / "
            "lax.cond, or mark the op @non_jittable",
            f"{kind}:{','.join(names)}",
            "definite" if in_ev else "possible")

    # -- closure capture ----------------------------------------------------
    def _free_loads(self):
        free = {}
        for n in self._body_nodes():
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id not in self.locals:
                free.setdefault(n.id, n)
        return free

    def _enclosing_binding_is_arrayish(self, name):
        """Best-effort: does `name` bind to an array/Tensor/PRNG key in an
        enclosing FUNCTION scope? Module-level captures are globals, not
        closure cells — skip them (TL003 covers mutation)."""
        for scope in self.scopes.scope_chain(self.fnode):
            if isinstance(scope, ast.ClassDef):
                continue
            if isinstance(scope, ast.Lambda):
                params, _ = func_params(scope)
                if name in params:
                    return bool(KEYISH_NAME.search(name))
                continue
            params, _ = func_params(scope)
            if name in params:
                return bool(KEYISH_NAME.search(name))
            for stmt in scope.body:
                for sub in ast.walk(stmt):
                    if sub is self.fnode:
                        break  # don't read our own body
                    if isinstance(sub, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == name
                            for t in sub.targets):
                        if self._value_is_arrayish(sub.value):
                            return True
            return False  # bound (or not) in nearest function scope: stop
        return False

    @staticmethod
    def _value_is_arrayish(v):
        """Does this binding expression produce a live array/Tensor/PRNG
        key? Deliberately narrow — `lax.conv_dimension_numbers(...)` and
        other static config objects captured from jnp/lax helpers are
        keyable and fine."""
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            if d and (d[-1] in ARRAY_PRODUCER_FUNCS
                      or "random" in d[:-1]
                      or any(KEYISH_NAME.search(p) for p in d)):
                return True
        if isinstance(v, ast.Attribute) and v.attr in ("_value", "grad"):
            return True
        return False

    def _check_closure_capture(self):
        for name, node in sorted(self._free_loads().items()):
            if self._enclosing_binding_is_arrayish(name):
                # PRNG-key captures get the concrete fix in the report:
                # a fresh key per call is usually BY DESIGN (dropout
                # semantics — caching would freeze randomness), so the
                # right move is recording that intent with
                # @non_jittable, not refactoring the key into an
                # argument. The symbol (and so the baseline
                # fingerprint) is unchanged.
                if KEYISH_NAME.search(name):
                    fix = ("; if the per-call key is intentional "
                           "(dropout-style randomness), decorate the op "
                           "with @non_jittable so the exemption is "
                           "explicit and the compile probe is never paid")
                else:
                    fix = "; pass it as an argument instead"
                self.report(
                    "closure-capture", node,
                    f"op body captures `{name}` (live array/PRNG key) "
                    "from an enclosing scope — the dispatch cache "
                    "refuses it, so this op pays eager dispatch every "
                    f"call{fix}",
                    f"capture:{name}", "possible")


# ---------------------------------------------------------------------------
# per-module driver

def _relpath(path, root_parent):
    rel = os.path.relpath(path, root_parent)
    return rel.replace(os.sep, "/")


def _suppressed(lines, lineno, rule):
    """Inline waiver: `# tracelint: ok` or `# tracelint: ok[slug,...]` on
    the flagged line waives the finding after human review."""
    if not 1 <= lineno <= len(lines):
        return False
    m = re.search(r"#\s*tracelint:\s*ok(\[([A-Za-z0-9_,\- ]+)\])?",
                  lines[lineno - 1])
    if not m:
        return False
    if m.group(2) is None:
        return True
    waived = {s.strip() for s in m.group(2).split(",")}
    return rule in waived or RULES[rule].id in waived


class ModuleAnalysis:
    def __init__(self, path, root_parent, audit_suspend=True):
        self.path = path
        self.relpath = _relpath(path, root_parent)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=path)
        self.scopes = _ScopeIndex(self.tree)
        self.audit_suspend = audit_suspend
        self.findings = []

    # -- op-body discovery --------------------------------------------------
    def _op_bodies(self):
        """{id(node): (node, context)} — dispatched op bodies and
        @non_jittable functions."""
        found = {}

        def add(node, context):
            if node is not None and id(node) not in found:
                found[id(node)] = (node, context)

        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and len(d) == 1 and d[0] in DISPATCH_NAMES and n.args:
                    tgt = n.args[0]
                    if isinstance(tgt, ast.Lambda):
                        add(tgt, "op-body")
                    elif isinstance(tgt, ast.Name):
                        add(self.scopes.resolve_function(tgt.id, n),
                            "op-body")
                # non_jittable(fn) direct-call form
                if d and d[-1] in NON_JITTABLE_NAMES and n.args \
                        and isinstance(n.args[0], ast.Name):
                    add(self.scopes.resolve_function(n.args[0].id, n),
                        "non-jittable")
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    dd = dotted(dec)
                    if dd and dd[-1] in NON_JITTABLE_NAMES:
                        add(n, "non-jittable")
        return list(found.values())

    # -- suspend audit ------------------------------------------------------
    def _suspending_helpers(self):
        """Module-level functions whose body enters dispatch.suspend() (or
        an already-known suspending helper): calls to them count as
        suspension for the audit."""
        names = set()
        for _ in range(2):  # one level of helper-calls-helper
            for stmt in self.tree.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if stmt.name in names:
                    continue
                if self._subtree_suspends(stmt, names):
                    names.add(stmt.name)
        return names

    @staticmethod
    def _subtree_suspends(node, helper_names=()):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and (d[-1] == "suspend" or d[-1] in helper_names):
                    return True
        return False

    def _audit_suspend_sites(self):
        helper_names = self._suspending_helpers()
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            if not d:
                continue
            is_entry = (d in TRACE_ENTRY_DOTTED
                        or (len(d) == 1 and d[0] in TRACE_ENTRY_BARE))
            if not is_entry:
                continue
            chain = self.scopes.scope_chain(n)
            scope = chain[-1] if chain else None
            if scope is not None:
                if self._subtree_suspends(scope, helper_names):
                    continue
                qual = self.scopes.qualname(scope)
                fname = getattr(scope, "name", "<lambda>")
                fline = scope.lineno
            else:
                # module-level trace call: scan its top-level statement
                stmt = n
                while not isinstance(self.scopes.parent.get(stmt),
                                     (ast.Module, type(None))):
                    stmt = self.scopes.parent[stmt]
                if self._subtree_suspends(stmt, helper_names):
                    continue
                qual, fname = "<module>", "<module>"
                fline = getattr(stmt, "lineno", 1)
            self.findings.append(Finding(
                rule="suspend-audit", path=self.relpath, line=n.lineno,
                col=n.col_offset, func=qual,
                func_name=fname,
                func_line=fline,
                message=f"{'.'.join(d)} traces user code with the per-op "
                        "dispatch cache live — wrap the traced body in "
                        "core.dispatch.suspend() (or waive with "
                        "`# tracelint: ok[suspend-audit]` if the traced "
                        "fn never dispatches paddle ops)",
                symbol="trace:" + ".".join(d),
                severity=RULES["suspend-audit"].severity,
                confidence="possible", context="trace-site"))

    # -- run ----------------------------------------------------------------
    def run(self):
        bodies = self._op_bodies()
        for node, context in bodies:
            checker = _OpBodyChecker(node, self.scopes, self.relpath,
                                     self.lines, self.findings, context)
            n_found = checker.run()
            if context == "non-jittable" and n_found == 0:
                self.findings.append(Finding(
                    rule="stale-non-jittable", path=self.relpath,
                    line=node.lineno, col=node.col_offset,
                    func=checker.qual, func_name=checker.func_name,
                    func_line=checker.func_line,
                    message="analysis finds no trace hazard in this "
                            "@non_jittable op — if the marking guards a "
                            "value-dependent shape, waive it; otherwise "
                            "drop it and let the op jit",
                    symbol="stale", severity="info",
                    confidence="possible", context="non-jittable"))
        if self.audit_suspend:
            self._audit_suspend_sites()
        for f in self.findings:
            f.suppressed = _suppressed(self.lines, f.line, f.rule)
        return self.findings


# ---------------------------------------------------------------------------
# tree driver

SKIP_DIRS = {"__pycache__", ".git", "libs", "include"}
# the dispatch/autograd machinery IS the cache — its jit sites are the
# implementation, not clients; auditing them is a tautology
AUDIT_EXEMPT_SUFFIXES = ("core/dispatch.py", "core/autograd.py",
                         "core/jax_compat.py")


def iter_py_files(root):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def analyze_paths(roots, audit_suspend=True):
    """Analyze every .py under each root. Returns (findings, errors):
    errors are (path, message) for unparseable files."""
    findings, errors = [], []
    for root in roots:
        root = os.path.normpath(root)
        root_parent = os.path.dirname(os.path.abspath(root))
        for path in iter_py_files(root):
            rel = _relpath(path, root_parent)
            audit = audit_suspend and not rel.endswith(AUDIT_EXEMPT_SUFFIXES)
            try:
                ma = ModuleAnalysis(path, root_parent, audit_suspend=audit)
                findings.extend(ma.run())
            except (SyntaxError, UnicodeDecodeError) as e:
                errors.append((rel, f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def analyze_file(path, audit_suspend=True):
    return analyze_paths([path], audit_suspend=audit_suspend)
