"""AST jit-safety analysis over the paddle_tpu op surface.

What counts as an "op body": any function object that can reach
`jax.jit` through the eager dispatch layer —

  * the first argument of a call to ``apply(...)`` / ``_apply(...)``
    (core.autograd.apply) or ``run_op(...)`` when it is a lambda or a
    name that resolves to a def/lambda in lexical scope;
  * any function marked ``@non_jittable`` (decorator or direct
    ``non_jittable(fn)`` call) — analyzed both for hazards and for
    staleness of the marking.

Within an op body the analysis runs a conservative name-level taint
pass (tools/staticlib/taint.py, bound to the jit sanitizer vocabulary
below): positional parameters without defaults are assumed traced
(arrays); parameters with defaults and closure statics are assumed
static.  Shape/dtype/ndim reads, ``len()``, ``isinstance()`` etc.
sanitize taint (they are static under trace).  Hazard visitors then
classify findings per rules.py; a finding is *definite* (manifest
grade) only when the hazard holds regardless of which argument is
traced (``.numpy()``, ``time.time()``, host randomness) or when it
touches a name the body itself treats as an array (passed to
jnp/lax/jax calls).

The pass is intentionally file-local and approximate: it must never
import the code it inspects (analysis of a broken tree is exactly when
lint is most useful), and false positives are absorbed by the checked
baseline rather than by weakening detection. The harness — scope
index, taint engine, fingerprints, waivers — is the shared
tools/staticlib core; only the jit-specific vocabulary and visitors
live here.
"""
from __future__ import annotations

import ast
import os
import re

from ..staticlib import findings as _findings
from ..staticlib.astnav import (
    ScopeIndex as _ScopeIndex, dotted, func_params, iter_py_files as
    _iter_py_files, relpath as _do_relpath, runtime_first_line,
)
from ..staticlib.taint import NameTaint, body_nodes
from ..staticlib.waivers import suppressed as _waiver_suppressed
from .rules import RULES

__all__ = ["Finding", "analyze_file", "analyze_paths", "iter_py_files"]


# ---------------------------------------------------------------------------
# model

class Finding(_findings.Finding):
    """tracelint finding: the shared record bound to the TL catalog."""

    RULES = RULES


# ---------------------------------------------------------------------------
# shared AST helpers

DISPATCH_NAMES = {"apply", "_apply", "run_op"}
NON_JITTABLE_NAMES = {"non_jittable"}

# attribute reads that are static under a jax trace — they sanitize taint
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "name",
                "itemsize", "nbytes"}
# calls whose result is host-static regardless of argument taint
# (shape/dtype queries are resolved at trace time, not run time)
SANITIZER_CALLS = {"len", "isinstance", "issubclass", "type", "id",
                   "repr", "str", "format", "hasattr", "callable",
                   "result_type", "issubdtype", "can_cast",
                   "promote_types", "iscomplexobj", "isrealobj",
                   "ndim", "shape", "finfo", "iinfo"}
# scalar coercions: hazardous only on a traced operand
COERCIONS = {"float", "int", "bool", "complex"}
HOST_METHODS = {"numpy", "item", "tolist"}
NP_HOST_FUNCS = {"asarray", "array", "asanyarray", "ascontiguousarray",
                 "frombuffer", "copyto", "save", "savez"}
IMPURE_MODULE_HEADS = {"time", "random", "secrets", "uuid", "datetime"}
MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop", "clear",
                    "update", "setdefault", "add", "discard", "popitem",
                    "write", "writelines", "sort", "reverse"}
ARRAY_METHODS = {"astype", "reshape", "sum", "mean", "transpose", "ravel",
                 "squeeze", "flatten", "min", "max", "at", "dot", "take",
                 "cumsum", "prod", "conj", "real", "imag", "round", "clip",
                 "numpy", "item", "tolist"}
ARRAY_CALL_HEADS = {"jnp", "jax", "lax", "_jnp", "jsp"}
MODULE_HEADS = ARRAY_CALL_HEADS | {"np", "numpy", "math", "os", "sys",
                                   "warnings", "collections", "itertools"}
KEYISH_NAME = re.compile(r"(^|_)(key|keys|rng|rngs|seed|prng)(_|$)|"
                         r"(^|_)(rand|noise)(_|$)")
ARRAY_PRODUCER_FUNCS = {"Tensor", "to_tensor", "asarray", "next_key",
                        "PRNGKey", "key", "split", "fold_in", "randn",
                        "rand", "uniform", "normal", "zeros", "ones",
                        "arange", "full", "empty"}

# whole-program trace entry points for the suspend audit
TRACE_ENTRY_DOTTED = {
    ("jax", "jit"), ("jax", "value_and_grad"), ("jax", "make_jaxpr"),
    ("jax", "eval_shape"), ("jax", "linearize"),
    ("lax", "cond"), ("lax", "switch"), ("lax", "while_loop"),
    ("jax", "lax", "cond"), ("jax", "lax", "switch"),
    ("jax", "lax", "while_loop"),
    ("jexport", "export"), ("export", "export"),
}
TRACE_ENTRY_BARE = {"shard_map"}


# ---------------------------------------------------------------------------
# per-op-body hazard analysis

class _OpBodyChecker:
    def __init__(self, fnode, scopes, relpath, lines, findings, context):
        self.fnode = fnode
        self.scopes = scopes
        self.relpath = relpath
        self.lines = lines
        self.findings = findings
        self.context = context
        self.qual = scopes.qualname(fnode)
        self.func_name = (fnode.name
                          if isinstance(fnode, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                          else "<lambda>")
        self.func_line = runtime_first_line(fnode)
        self.n_found = 0

        # shared taint engine, bound to the jit sanitizer vocabulary
        self.taint = NameTaint(fnode, static_attrs=STATIC_ATTRS,
                               sanitizer_calls=SANITIZER_CALLS,
                               coercions=COERCIONS,
                               host_methods=HOST_METHODS)
        self.params = self.taint.params
        self.tainted = self.taint.tainted
        self.vararg = self.taint.vararg
        self.locals = self.taint.locals
        self.array_evidence = self._collect_array_evidence()

    # -- scope bookkeeping --------------------------------------------------
    def _body_nodes(self):
        yield from body_nodes(self.fnode)

    def _collect_array_evidence(self):
        """Names the body itself treats as arrays: fed to jnp/lax/jax
        calls or used with array methods."""
        ev = set()
        for n in self._body_nodes():
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and d[0] in ARRAY_CALL_HEADS:
                    for a in list(n.args) + [kw.value for kw in n.keywords]:
                        for nm in ast.walk(a):
                            if isinstance(nm, ast.Name):
                                ev.add(nm.id)
            if isinstance(n, ast.Attribute) and n.attr in ARRAY_METHODS \
                    and isinstance(n.value, ast.Name):
                ev.add(n.value.id)
            if isinstance(n, ast.BinOp):
                for side in (n.left, n.right):
                    if isinstance(side, ast.Name):
                        ev.add(side.id)
        return ev

    # -- taint query --------------------------------------------------------
    def expr_tainted(self, node):
        return self.taint.expr_tainted(node)

    def _taint_names(self, node):
        return self.taint.taint_names(node)

    # -- reporting ----------------------------------------------------------
    def report(self, rule, node, message, symbol, confidence):
        sev = RULES[rule].severity
        self.findings.append(Finding(
            rule=rule, path=self.relpath, line=node.lineno,
            col=node.col_offset, func=self.qual, func_name=self.func_name,
            func_line=self.func_line, message=message, symbol=symbol,
            severity=sev, confidence=confidence, context=self.context))
        self.n_found += 1

    # -- the checks ---------------------------------------------------------
    def run(self):
        self._check_declared_state()
        for n in self._body_nodes():
            if isinstance(n, ast.Call):
                self._check_call(n)
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                self._check_store(n)
            elif isinstance(n, ast.If):
                self._check_branch(n, n.test, "if")
            elif isinstance(n, ast.While):
                self._check_branch(n, n.test, "while")
            elif isinstance(n, ast.IfExp):
                self._check_branch(n, n.test, "ternary")
            elif isinstance(n, ast.Assert):
                self._check_branch(n, n.test, "assert")
            elif isinstance(n, ast.For):
                if self.expr_tainted(n.iter):
                    self.report(
                        "data-dependent-control-flow", n,
                        "for-loop iterates over a traced value "
                        f"({', '.join(self._taint_names(n.iter))}) — the "
                        "trace unrolls per element or fails on dynamic "
                        "length", "for:" + ",".join(self._taint_names(n.iter)),
                        "possible")
        self._check_closure_capture()
        return self.n_found

    def _check_declared_state(self):
        for n in self._body_nodes():
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(n, ast.Global) else "nonlocal"
                self.report(
                    "state-mutation", n,
                    f"`{kind} {', '.join(n.names)}` inside an op body — "
                    "the rebind happens once at trace time, then never "
                    "again in the compiled program",
                    f"{kind}:{','.join(n.names)}", "definite")

    def _check_call(self, n):
        d = dotted(n.func)
        # .numpy()/.item()/.tolist() — host sync no matter which operand
        if isinstance(n.func, ast.Attribute) and n.func.attr in HOST_METHODS:
            base = n.func.value
            base_d = dotted(base)
            if base_d and base_d[0] in IMPURE_MODULE_HEADS:
                pass  # e.g. datetime.date.today().tolist() — TL004 below
            else:
                conf = ("definite"
                        if self.expr_tainted(base)
                        or (isinstance(base, ast.Name)
                            and base.id in self.array_evidence)
                        else "possible")
                self.report(
                    "host-materialize", n,
                    f".{n.func.attr}() forces a host transfer inside a "
                    "potentially-traced op body (fails on tracers, "
                    "de-optimizes on arrays)",
                    f".{n.func.attr}", conf)
                return
        # float(x)/int(x)/bool(x) on traced values
        if d and len(d) == 1 and d[0] in COERCIONS and n.args:
            if self.expr_tainted(n.args[0]):
                names = self._taint_names(n.args[0])
                in_ev = any(nm in self.array_evidence for nm in names)
                self.report(
                    "host-materialize", n,
                    f"{d[0]}() on a traced value "
                    f"({', '.join(names)}) raises "
                    "ConcretizationTypeError under trace",
                    f"{d[0]}:{','.join(names)}",
                    "definite" if in_ev else "possible")
                return
        # np.asarray & friends on traced values
        if d and len(d) >= 2 and d[0] in ("np", "numpy") \
                and d[-1] in NP_HOST_FUNCS:
            if len(d) >= 2 and d[1] == "random":
                pass  # np.random.* handled as impurity below
            elif any(self.expr_tainted(a) for a in n.args):
                names = [nm for a in n.args for nm in self._taint_names(a)]
                self.report(
                    "host-materialize", n,
                    f"{'.'.join(d)} materializes a traced value "
                    f"({', '.join(names)}) on host",
                    ".".join(d), "definite")
                return
        # wall clock / host randomness
        if d and d[0] in IMPURE_MODULE_HEADS and len(d) >= 2:
            self.report(
                "impure-call", n,
                f"{'.'.join(d)}() inside an op body — the value is "
                "frozen at trace time and replayed by every cached call",
                ".".join(d), "definite")
            return
        if d and len(d) >= 3 and d[0] in ("np", "numpy") and d[1] == "random":
            self.report(
                "impure-call", n,
                f"{'.'.join(d)}() — numpy host randomness freezes into "
                "the compiled program; thread a jax PRNG key instead",
                ".".join(d), "definite")
            return
        if d and d[0] == "os" and d[-1] == "urandom":
            self.report("impure-call", n, "os.urandom inside an op body",
                        "os.urandom", "definite")
            return
        # mutating method on a free (captured) name — but not on a
        # module (jnp.sort is numpy-API sort, not list mutation)
        if isinstance(n.func, ast.Attribute) \
                and n.func.attr in MUTATING_METHODS \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id not in self.locals \
                and n.func.value.id not in MODULE_HEADS:
            self.report(
                "state-mutation", n,
                f"`{n.func.value.id}.{n.func.attr}(...)` mutates captured "
                "state — runs once at trace time, never per compiled call",
                f"{n.func.value.id}.{n.func.attr}", "possible")

    def _check_store(self, n):
        tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
        for t in tgts:
            root = t
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if root is t:
                continue  # plain name store: local, fine
            if isinstance(root, ast.Name) and root.id not in self.locals:
                kind = ("attribute"
                        if isinstance(t, ast.Attribute) else "subscript")
                self.report(
                    "state-mutation", n,
                    f"{kind} store on captured `{root.id}` inside an op "
                    "body — the write happens at trace time only",
                    f"store:{root.id}", "definite")

    def _check_branch(self, node, test, kind):
        if not self.expr_tainted(test):
            return
        names = self._taint_names(test)
        in_ev = any(nm in self.array_evidence for nm in names)
        self.report(
            "data-dependent-control-flow", node,
            f"`{kind}` on a traced value ({', '.join(names)}) — "
            "TracerBoolConversionError under trace; use jnp.where / "
            "lax.cond, or mark the op @non_jittable",
            f"{kind}:{','.join(names)}",
            "definite" if in_ev else "possible")

    # -- closure capture ----------------------------------------------------
    def _free_loads(self):
        free = {}
        for n in self._body_nodes():
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id not in self.locals:
                free.setdefault(n.id, n)
        return free

    def _enclosing_binding_is_arrayish(self, name):
        """Best-effort: does `name` bind to an array/Tensor/PRNG key in an
        enclosing FUNCTION scope? Module-level captures are globals, not
        closure cells — skip them (TL003 covers mutation)."""
        for scope in self.scopes.scope_chain(self.fnode):
            if isinstance(scope, ast.ClassDef):
                continue
            if isinstance(scope, ast.Lambda):
                params, _ = func_params(scope)
                if name in params:
                    return bool(KEYISH_NAME.search(name))
                continue
            params, _ = func_params(scope)
            if name in params:
                return bool(KEYISH_NAME.search(name))
            for stmt in scope.body:
                for sub in ast.walk(stmt):
                    if sub is self.fnode:
                        break  # don't read our own body
                    if isinstance(sub, ast.Assign) and any(
                            isinstance(t, ast.Name) and t.id == name
                            for t in sub.targets):
                        if self._value_is_arrayish(sub.value):
                            return True
            return False  # bound (or not) in nearest function scope: stop
        return False

    @staticmethod
    def _value_is_arrayish(v):
        """Does this binding expression produce a live array/Tensor/PRNG
        key? Deliberately narrow — `lax.conv_dimension_numbers(...)` and
        other static config objects captured from jnp/lax helpers are
        keyable and fine."""
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            if d and (d[-1] in ARRAY_PRODUCER_FUNCS
                      or "random" in d[:-1]
                      or any(KEYISH_NAME.search(p) for p in d)):
                return True
        if isinstance(v, ast.Attribute) and v.attr in ("_value", "grad"):
            return True
        return False

    def _check_closure_capture(self):
        for name, node in sorted(self._free_loads().items()):
            if self._enclosing_binding_is_arrayish(name):
                # PRNG-key captures get the concrete fix in the report:
                # a fresh key per call is usually BY DESIGN (dropout
                # semantics — caching would freeze randomness), so the
                # right move is recording that intent with
                # @non_jittable, not refactoring the key into an
                # argument. The symbol (and so the baseline
                # fingerprint) is unchanged.
                if KEYISH_NAME.search(name):
                    fix = ("; if the per-call key is intentional "
                           "(dropout-style randomness), decorate the op "
                           "with @non_jittable so the exemption is "
                           "explicit and the compile probe is never paid")
                else:
                    fix = "; pass it as an argument instead"
                self.report(
                    "closure-capture", node,
                    f"op body captures `{name}` (live array/PRNG key) "
                    "from an enclosing scope — the dispatch cache "
                    "refuses it, so this op pays eager dispatch every "
                    f"call{fix}",
                    f"capture:{name}", "possible")


# ---------------------------------------------------------------------------
# per-module driver

def _relpath(path, root_parent):
    return _do_relpath(path, root_parent)


def _suppressed(lines, lineno, rule):
    """Inline waiver: `# tracelint: ok` or `# tracelint: ok[slug,...]` on
    the flagged line waives the finding after human review (shared
    machinery: tools/staticlib/waivers.py)."""
    return _waiver_suppressed(lines, lineno, rule, "tracelint", RULES)


class ModuleAnalysis:
    def __init__(self, path, root_parent, audit_suspend=True):
        self.path = path
        self.relpath = _relpath(path, root_parent)
        with open(path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=path)
        self.scopes = _ScopeIndex(self.tree)
        self.audit_suspend = audit_suspend
        self.findings = []

    # -- op-body discovery --------------------------------------------------
    def _op_bodies(self):
        """{id(node): (node, context)} — dispatched op bodies and
        @non_jittable functions."""
        found = {}

        def add(node, context):
            if node is not None and id(node) not in found:
                found[id(node)] = (node, context)

        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and len(d) == 1 and d[0] in DISPATCH_NAMES and n.args:
                    tgt = n.args[0]
                    if isinstance(tgt, ast.Lambda):
                        add(tgt, "op-body")
                    elif isinstance(tgt, ast.Name):
                        add(self.scopes.resolve_function(tgt.id, n),
                            "op-body")
                # non_jittable(fn) direct-call form
                if d and d[-1] in NON_JITTABLE_NAMES and n.args \
                        and isinstance(n.args[0], ast.Name):
                    add(self.scopes.resolve_function(n.args[0].id, n),
                        "non-jittable")
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    dd = dotted(dec)
                    if dd and dd[-1] in NON_JITTABLE_NAMES:
                        add(n, "non-jittable")
        return list(found.values())

    # -- suspend audit ------------------------------------------------------
    def _suspending_helpers(self):
        """Module-level functions whose body enters dispatch.suspend() (or
        an already-known suspending helper): calls to them count as
        suspension for the audit."""
        names = set()
        for _ in range(2):  # one level of helper-calls-helper
            for stmt in self.tree.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if stmt.name in names:
                    continue
                if self._subtree_suspends(stmt, names):
                    names.add(stmt.name)
        return names

    @staticmethod
    def _subtree_suspends(node, helper_names=()):
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d and (d[-1] == "suspend" or d[-1] in helper_names):
                    return True
        return False

    def _audit_suspend_sites(self):
        helper_names = self._suspending_helpers()
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            if not d:
                continue
            is_entry = (d in TRACE_ENTRY_DOTTED
                        or (len(d) == 1 and d[0] in TRACE_ENTRY_BARE))
            if not is_entry:
                continue
            chain = self.scopes.scope_chain(n)
            scope = chain[-1] if chain else None
            if scope is not None:
                if self._subtree_suspends(scope, helper_names):
                    continue
                qual = self.scopes.qualname(scope)
                fname = getattr(scope, "name", "<lambda>")
                fline = scope.lineno
            else:
                # module-level trace call: scan its top-level statement
                stmt = n
                while not isinstance(self.scopes.parent.get(stmt),
                                     (ast.Module, type(None))):
                    stmt = self.scopes.parent[stmt]
                if self._subtree_suspends(stmt, helper_names):
                    continue
                qual, fname = "<module>", "<module>"
                fline = getattr(stmt, "lineno", 1)
            self.findings.append(Finding(
                rule="suspend-audit", path=self.relpath, line=n.lineno,
                col=n.col_offset, func=qual,
                func_name=fname,
                func_line=fline,
                message=f"{'.'.join(d)} traces user code with the per-op "
                        "dispatch cache live — wrap the traced body in "
                        "core.dispatch.suspend() (or waive with "
                        "`# tracelint: ok[suspend-audit]` if the traced "
                        "fn never dispatches paddle ops)",
                symbol="trace:" + ".".join(d),
                severity=RULES["suspend-audit"].severity,
                confidence="possible", context="trace-site"))

    # -- run ----------------------------------------------------------------
    def run(self):
        bodies = self._op_bodies()
        for node, context in bodies:
            checker = _OpBodyChecker(node, self.scopes, self.relpath,
                                     self.lines, self.findings, context)
            n_found = checker.run()
            if context == "non-jittable" and n_found == 0:
                self.findings.append(Finding(
                    rule="stale-non-jittable", path=self.relpath,
                    line=node.lineno, col=node.col_offset,
                    func=checker.qual, func_name=checker.func_name,
                    func_line=checker.func_line,
                    message="analysis finds no trace hazard in this "
                            "@non_jittable op — if the marking guards a "
                            "value-dependent shape, waive it; otherwise "
                            "drop it and let the op jit",
                    symbol="stale", severity="info",
                    confidence="possible", context="non-jittable"))
        if self.audit_suspend:
            self._audit_suspend_sites()
        for f in self.findings:
            f.suppressed = _suppressed(self.lines, f.line, f.rule)
        return self.findings


# ---------------------------------------------------------------------------
# tree driver

SKIP_DIRS = {"__pycache__", ".git", "libs", "include"}
# the dispatch/autograd machinery IS the cache — its jit sites are the
# implementation, not clients; auditing them is a tautology
AUDIT_EXEMPT_SUFFIXES = ("core/dispatch.py", "core/autograd.py",
                         "core/jax_compat.py")


def iter_py_files(root):
    yield from _iter_py_files(root, skip_dirs=SKIP_DIRS)


def analyze_paths(roots, audit_suspend=True):
    """Analyze every .py under each root. Returns (findings, errors):
    errors are (path, message) for unparseable files."""
    findings, errors = [], []
    for root in roots:
        root = os.path.normpath(root)
        root_parent = os.path.dirname(os.path.abspath(root))
        for path in iter_py_files(root):
            rel = _relpath(path, root_parent)
            audit = audit_suspend and not rel.endswith(AUDIT_EXEMPT_SUFFIXES)
            try:
                ma = ModuleAnalysis(path, root_parent, audit_suspend=audit)
                findings.extend(ma.run())
            except (SyntaxError, UnicodeDecodeError) as e:
                errors.append((rel, f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, errors


def analyze_file(path, audit_suspend=True):
    return analyze_paths([path], audit_suspend=audit_suspend)
