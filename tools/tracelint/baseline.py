"""Baseline (suppression) file handling for tracelint.

The mechanics — fingerprint multiset, EXCEEDS-count gating, stale-entry
reporting — are the shared tools/staticlib/baseline.py core (see its
docstring for the contract); this module binds tracelint's default path
and regenerate hint.
"""
from __future__ import annotations

import os

from ..staticlib.baseline import (  # noqa: F401 — re-exported API
    BASELINE_VERSION, load_baseline, partition,
)
from ..staticlib.baseline import write_baseline as _write_baseline

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

_COMMENT = ("tracelint suppression baseline — regenerate with "
            "`python -m tools.tracelint paddle_tpu "
            "--write-baseline` after reviewing that every new "
            "finding is intended debt, not a regression.")


def write_baseline(path, findings):
    """Snapshot current non-suppressed, non-info findings as the new
    baseline (info findings never gate, so baselining them is noise)."""
    return _write_baseline(path, findings, _COMMENT)
