"""Rule catalog for tracelint.

Each rule names one class of trace-hygiene hazard in code the eager
dispatch layer (paddle_tpu/core/dispatch.py) may hand to `jax.jit`.
The catalog is data, not behavior — detection lives in analyzer.py —
so docs, reports and the baseline speak one vocabulary. The Rule
dataclass and severity vocabulary are shared with every other analyzer
via tools/staticlib.

`manifest` marks rules whose *definite* findings feed the generated
static unjittable manifest (paddle_tpu/core/_unjittable_manifest.py):
only hazards that hold regardless of which argument happens to be
traced may pre-demote an op to eager for the process lifetime.
"""
from __future__ import annotations

from ..staticlib.rules import Rule, ruleset

RULES, BY_ID, get = ruleset([
    Rule("TL001", "host-materialize", "error", True,
         "host materialization inside a potentially-traced op body "
         "(.numpy()/.item()/.tolist(), float()/int()/bool() on a "
         "traced value, np.asarray on a traced value)"),
    Rule("TL002", "closure-capture", "warning", False,
         "op body captures a live array / Tensor / PRNG key from an "
         "enclosing scope — the dispatch cache refuses such ops, so "
         "every call pays eager dispatch (and a frozen capture would "
         "bake stale state)"),
    Rule("TL003", "state-mutation", "error", False,
         "op body mutates nonlocal/global/module state — under "
         "jax.jit the side effect runs once at trace time, then "
         "never again"),
    Rule("TL004", "impure-call", "error", True,
         "wall-clock / host randomness inside a potentially-traced "
         "op body (time.*, random.*, np.random.*, uuid/secrets) — "
         "the value freezes into the compiled program"),
    Rule("TL005", "data-dependent-control-flow", "warning", False,
         "Python if/while/for branches on a traced value — trace "
         "raises TracerBoolConversionError (one failed compile "
         "probe) or, for shape-dependent code, silently "
         "specializes"),
    Rule("TL006", "stale-non-jittable", "info", False,
         "@non_jittable decoration on an op the analysis finds no "
         "hazard in — possibly stale, costing jit caching for "
         "nothing"),
    Rule("TL007", "suspend-audit", "warning", False,
         "whole-program trace site (jax.jit / shard_map / lax "
         "control flow over user callables) without a "
         "dispatch.suspend() in reach — per-op dispatch inside the "
         "trace burns cache keys on throwaway tracer avals"),
])

__all__ = ["Rule", "RULES", "BY_ID", "get"]
