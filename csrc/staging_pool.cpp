// Host staging ring buffer for the input pipeline.
//
// Reference capability: paddle/fluid/memory/allocation/pinned_allocator.cc +
// fluid/operators/reader/buffered_reader.cc (pinned staging buffers that
// overlap batch assembly with device transfer). TPU-native equivalent: a
// fixed pool of 64-byte-aligned host slots that worker threads memcpy
// collated batches into (ctypes calls drop the GIL, so copies from N workers
// run truly in parallel), handed to the consumer FIFO for a zero-copy
// np.frombuffer view feeding jax.device_put. Fixed slots mean no per-batch
// malloc/free of multi-MB arrays and stable, aligned source addresses for
// the XLA host-to-device DMA.
//
// C API (ctypes-friendly): sp_create / sp_destroy / sp_acquire_write /
// sp_slot_ptr / sp_commit / sp_acquire_read / sp_release / sp_copy_in.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct StagingPool {
  size_t slot_bytes;
  std::vector<void*> slots;
  std::deque<int> free_q;
  std::deque<int> ready_q;
  std::mutex mu;
  std::condition_variable free_cv;
  std::condition_variable ready_cv;
  // counts threads anywhere inside sp_acquire_* — incremented BEFORE the
  // mutex is taken, so sp_destroy seeing 0 after `closed` means no thread
  // can still touch the pool (callers must not start new calls after
  // destroy; the Python wrapper nulls its handle first)
  std::atomic<int> inflight{0};
  bool closed = false;
};

struct InflightGuard {
  std::atomic<int>& c;
  explicit InflightGuard(std::atomic<int>& c) : c(c) { c.fetch_add(1); }
  ~InflightGuard() { c.fetch_sub(1); }
};

bool wait_pop(StagingPool* p, std::deque<int>& q, std::condition_variable& cv,
              int timeout_ms, int* out) {
  std::unique_lock<std::mutex> lk(p->mu);
  auto ready = [&] { return !q.empty() || p->closed; };
  bool ok = true;
  if (timeout_ms < 0) {
    cv.wait(lk, ready);
  } else {
    ok = cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
  }
  if (!ok || q.empty()) return false;  // timeout or closed
  *out = q.front();
  q.pop_front();
  return true;
}

}  // namespace

extern "C" {

void* sp_create(int n_slots, size_t slot_bytes) {
  if (n_slots <= 0 || slot_bytes == 0) return nullptr;
  auto* p = new StagingPool();
  p->slot_bytes = slot_bytes;
  p->slots.reserve(n_slots);
  for (int i = 0; i < n_slots; ++i) {
    void* buf = nullptr;
    if (posix_memalign(&buf, 64, slot_bytes) != 0) {
      for (void* b : p->slots) free(b);
      delete p;
      return nullptr;
    }
    p->slots.push_back(buf);
    p->free_q.push_back(i);
  }
  return p;
}

void sp_destroy(void* pool) {
  auto* p = static_cast<StagingPool*>(pool);
  if (!p) return;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->closed = true;
    p->free_cv.notify_all();
    p->ready_cv.notify_all();
  }
  // wait for every thread already inside sp_acquire_* (counted before it
  // takes the mutex) to leave before freeing — otherwise woken waiters
  // touch freed memory
  while (p->inflight.load() > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  for (void* b : p->slots) free(b);
  delete p;
}

size_t sp_slot_bytes(void* pool) {
  return static_cast<StagingPool*>(pool)->slot_bytes;
}

int sp_num_slots(void* pool) {
  return static_cast<int>(static_cast<StagingPool*>(pool)->slots.size());
}

// Returns a free slot id to fill, or -1 on timeout/closed.
int sp_acquire_write(void* pool, int timeout_ms) {
  auto* p = static_cast<StagingPool*>(pool);
  InflightGuard g(p->inflight);
  int slot = -1;
  return wait_pop(p, p->free_q, p->free_cv, timeout_ms, &slot) ? slot : -1;
}

void* sp_slot_ptr(void* pool, int slot) {
  return static_cast<StagingPool*>(pool)->slots[slot];
}

// Parallel-friendly copy into a slot region; runs GIL-free under ctypes.
int sp_copy_in(void* pool, int slot, size_t offset, const void* src,
               size_t nbytes) {
  auto* p = static_cast<StagingPool*>(pool);
  if (offset + nbytes > p->slot_bytes) return -1;
  memcpy(static_cast<char*>(p->slots[slot]) + offset, src, nbytes);
  return 0;
}

// Publish a filled slot to the consumer (FIFO).
void sp_commit(void* pool, int slot) {
  auto* p = static_cast<StagingPool*>(pool);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->ready_q.push_back(slot);
  }
  p->ready_cv.notify_one();
}

// Returns the oldest committed slot, or -1 on timeout/closed.
int sp_acquire_read(void* pool, int timeout_ms) {
  auto* p = static_cast<StagingPool*>(pool);
  InflightGuard g(p->inflight);
  int slot = -1;
  return wait_pop(p, p->ready_q, p->ready_cv, timeout_ms, &slot) ? slot : -1;
}

// Return a consumed slot to the free list.
void sp_release(void* pool, int slot) {
  auto* p = static_cast<StagingPool*>(pool);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->free_q.push_back(slot);
  }
  p->free_cv.notify_one();
}

}  // extern "C"
