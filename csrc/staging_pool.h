// C API of the pinned-host staging ring (csrc/staging_pool.cpp) — the
// input-pipeline buffer pool paddle_tpu's DataLoader uses to overlap
// host collate with device transfer. Link against the cpp_extension-built
// shared object; see paddle_tpu/utils/cpp_extension.py for the loader.
#pragma once

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

// Create a ring of n_slots aligned host buffers of slot_bytes each.
// Returns an opaque pool handle, or NULL on invalid arguments.
void* sp_create(int n_slots, size_t slot_bytes);
void sp_destroy(void* pool);

size_t sp_slot_bytes(void* pool);
int sp_num_slots(void* pool);

// Producer side: acquire a writable slot (-1 on timeout), fill it with
// sp_copy_in (GIL-free parallel memcpy) at byte offsets, then commit.
int sp_acquire_write(void* pool, int timeout_ms);
void* sp_slot_ptr(void* pool, int slot);
int sp_copy_in(void* pool, int slot, size_t offset, const void* src,
               size_t nbytes);
void sp_commit(void* pool, int slot);

// Consumer side: acquire the oldest committed slot (-1 on timeout),
// read through sp_slot_ptr, then release it back to the ring.
int sp_acquire_read(void* pool, int timeout_ms);
void sp_release(void* pool, int slot);

#ifdef __cplusplus
}  // extern "C"
#endif
