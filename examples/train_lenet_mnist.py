"""Single-chip training with the high-level API (paddle.Model.fit).

Run: python examples/train_lenet_mnist.py
Everything compiles into ONE XLA program per step (forward, loss,
backward, optimizer update) with donated buffers.
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import paddle_tpu as paddle
from paddle_tpu import nn


def main():
    paddle.seed(0)
    train = paddle.vision.datasets.MNIST(mode="train")
    test = paddle.vision.datasets.MNIST(mode="test")

    model = paddle.Model(paddle.vision.models.LeNet())
    model.prepare(paddle.optimizer.Adam(parameters=model.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    model.fit(train, epochs=1, batch_size=128, verbose=1)
    print(model.evaluate(test, batch_size=128, verbose=0))


if __name__ == "__main__":
    main()
