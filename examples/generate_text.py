"""Batched autoregressive decoding with the jitted static-shape KV cache.

Run: python examples/generate_text.py
Prefill compiles once per prompt length; every subsequent token reuses one
cached XLA executable (preallocated caches + dynamic_update_slice).
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM


def main():
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(vocab_size=50304, hidden_size=256,
                                     num_layers=4, num_heads=8,
                                     max_position=256, dropout=0.0))
    model.eval()
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 50304, (4, 16)))
    out = model.generate(prompt, max_new_tokens=32, top_k=40,
                         temperature=0.9)
    print("top-k ids:", np.asarray(out.numpy())[0, -8:])
    out = model.generate(prompt, max_new_tokens=32, top_p=0.9)
    print("top-p ids:", np.asarray(out.numpy())[0, -8:])
    out = model.generate(prompt, max_new_tokens=32, num_beams=4,
                         length_penalty=0.8)
    print("beam-4 ids:", np.asarray(out.numpy())[0, -8:])


if __name__ == "__main__":
    main()
