"""Shared example bootstrap.

`python examples/foo.py` puts examples/ (not the repo root) on
sys.path — repo_root() fixes the import path. CPU forcing must go
through jax.config: plugin registration à la sitecustomize runs at
interpreter start, so a JAX_PLATFORMS env var set here is too late.
"""
import os
import sys


def repo_root():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


def force_cpu(devices=1):
    if devices > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def maybe_force_cpu():
    """Opt-in CPU run for the single-chip examples (smoke tests, judge
    machines without the TPU tunnel): PADDLE_TPU_EXAMPLE_CPU=1."""
    if os.environ.get("PADDLE_TPU_EXAMPLE_CPU") == "1":
        force_cpu()
