"""A reference-era (1.x) fluid script running unmodified on paddle_tpu.

Demonstrates the compat namespace: static Program + Executor and the
dygraph guard/to_variable idiom, both through `paddle_tpu.fluid`.
Run: python examples/train_fluid_era_mnist.py
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def synth_mnist(n=256, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 1, 28, 28).astype("float32"),
            rng.randint(0, 10, (n, 1)).astype("int64"))


def static_mnist():
    paddle.enable_static()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        hidden = fluid.layers.fc(img, size=128, activation="relu")
        pred = fluid.layers.fc(hidden, size=10, activation="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        acc = fluid.layers.accuracy(input=pred, label=label)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    x, y = synth_mnist()
    for step in range(10):
        lv, av = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[loss, acc])
        if step % 3 == 0:
            print(f"[static] step {step} loss {float(lv):.4f} "
                  f"acc {float(np.asarray(av).ravel()[0]):.3f}")
    paddle.disable_static()


def dygraph_mnist():
    with fluid.dygraph.guard():
        paddle.seed(0)

        class MNIST(fluid.dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.conv = fluid.dygraph.Conv2D(1, 16, 3, padding=1,
                                                 act="relu")
                self.pool = fluid.dygraph.Pool2D(2, "max", 2)
                self.fc = fluid.dygraph.Linear(16 * 14 * 14, 10,
                                               act="softmax")

            def forward(self, x):
                x = self.pool(self.conv(x))
                return self.fc(fluid.layers.reshape(x, [x.shape[0], -1]))

        model = MNIST()
        opt = fluid.optimizer.AdamOptimizer(
            learning_rate=1e-3, parameter_list=model.parameters())
        x, y = synth_mnist(seed=1)
        for step in range(10):
            img = fluid.dygraph.to_variable(x)
            label = fluid.dygraph.to_variable(y)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(model(img), label))
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            if step % 3 == 0:
                print(f"[dygraph] step {step} loss {float(loss):.4f}")


if __name__ == "__main__":
    static_mnist()
    dygraph_mnist()
    print("fluid-era script ran end-to-end on paddle_tpu")
