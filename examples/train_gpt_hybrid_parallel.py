"""Hybrid-parallel GPT training through the fleet API.

Run: python examples/train_gpt_hybrid_parallel.py
(defaults to a virtual 8-device CPU mesh so it runs anywhere; set
PADDLE_TPU_EXAMPLE_REAL=1 on a real 8-chip host)

fleet.init turns the strategy into a (dp, pp, tp) device mesh; the model's
sharding annotations resolve against it (megatron tp layout), the trunk
becomes a PipelineLayer running a jitted GPipe schedule, and XLA inserts
the collectives.
"""
import os

import _bootstrap  # noqa: examples/ is sys.path[0] for script runs

_bootstrap.repo_root()
if os.environ.get("PADDLE_TPU_EXAMPLE_REAL") != "1":
    _bootstrap.force_cpu(devices=8)

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM


def main():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                    num_heads=8, max_position=128, dropout=0.0)
    model = fleet.distributed_model(GPTForCausalLM(cfg))
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters()))

    rng = np.random.RandomState(0)
    for step in range(10):
        ids = paddle.to_tensor(rng.randint(0, 1024, (8, 64)))
        labels = paddle.to_tensor(rng.randint(0, 1024, (8, 64)))
        loss = model(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
