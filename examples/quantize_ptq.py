"""Post-training int8 quantization.

Run: python examples/quantize_ptq.py
Calibration observers ride the normal (jitted) eval forwards; convert()
swaps Linear/Conv2D for int8 layers whose matmuls lower to the MXU's
integer dot_general.
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.quantization import ImperativePTQ, QuantConfig


def main():
    paddle.seed(0)
    model = paddle.vision.models.LeNet()
    model.eval()

    ptq = ImperativePTQ(QuantConfig(activation_quantize_type="hist"))
    ptq.quantize(model)
    rng = np.random.RandomState(0)
    for _ in range(8):  # calibration sweep
        model(paddle.to_tensor(
            rng.randn(16, 1, 28, 28).astype(np.float32)))
    model = ptq.convert(model)

    x = paddle.to_tensor(rng.randn(4, 1, 28, 28).astype(np.float32))
    print("int8 logits:", np.asarray(model(x).numpy())[0, :4])


if __name__ == "__main__":
    main()
