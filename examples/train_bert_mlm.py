"""BERT-base masked-LM pretraining step — the headline bench config.

Run: python examples/train_bert_mlm.py [--steps N]
Shows the flagship training path end-to-end: AMP O2 (bf16 weights, f32
norm statistics with bf16 activations), the blockwise fused LM-head CE
(no [batch*seq, vocab] logits buffer; the decoder bias rides the
kernel's bias argument), and a whole-step donated jit — forward, loss,
backward, AdamW in ONE XLA program. Synthetic token data keeps it
zero-egress; loss falls from ~ln(vocab) as the model memorizes the
batch distribution.
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import argparse

import numpy as np

import paddle_tpu as paddle


def main(steps=8, batch=4, seq=64):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    # a slim BERT so the example runs in seconds on CPU; the bench's
    # full base config is the same code path (models/bert.py)
    cfg = BertConfig(hidden_size=128, num_layers=2, num_heads=2,
                     intermediate_size=256, max_position=seq,
                     dropout=0.0, attention_dropout=0.0)
    model = BertForMaskedLM(cfg)
    paddle.amp.decorate(model, level="O2")
    model.eval()  # dropout off; MLM has no batch-norm stats

    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            with paddle.no_grad():
                out = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, None, Tensor(labels))[0]
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)

        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st,
                                             jnp.float32(5e-4), meta=meta)
        return new_p, new_s, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = np.tile(rng.randint(0, cfg.vocab_size, (1, seq)), (batch, 1))
    labels = ids.copy()  # predict-everything MLM keeps the example tiny

    first = loss = None
    for i in range(steps):
        params, states, loss = jit_step(params, states, ids, labels)
        loss = float(loss)
        first = loss if first is None else first
        print(f"step {i}: mlm_loss={loss:.4f}")
    if steps > 1:
        assert loss < first, (first, loss)
        print("loss decreased — fused-CE AMP-O2 step trains")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    main(steps=ap.parse_args().steps)
