"""Train the seq2seq Transformer on WMT16 (synthetic fallback corpus)
and translate — the reference's machine-translation benchmark flow on
paddle_tpu. Run: python examples/train_wmt_transformer.py
"""
import _bootstrap  # noqa: examples/ is sys.path[0] for script runs
_bootstrap.repo_root()
_bootstrap.maybe_force_cpu()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.transformer import TransformerConfig, TransformerModel
from paddle_tpu.text.datasets import WMT16


def pad_batch(seqs, pad, width):
    out = np.full((len(seqs), width), pad, np.int64)
    for i, s in enumerate(seqs):
        out[i, :min(len(s), width)] = np.asarray(s)[:width]
    return out


def main():
    paddle.seed(0)
    V = 120
    ds = WMT16(mode="train", src_dict_size=V, trg_dict_size=V)
    cfg = TransformerConfig(src_vocab_size=V, tgt_vocab_size=V,
                            d_model=64, nhead=4, num_encoder_layers=2,
                            num_decoder_layers=2, dim_feedforward=128,
                            dropout=0.0, max_length=32,
                            bos_id=0, eos_id=1, pad_id=2)
    model = TransformerModel(cfg)
    model.eval()  # dropout off; deterministic demo
    opt = paddle.optimizer.Adam(learning_rate=5e-4,
                                parameters=model.parameters())

    # one padded batch, trained to overfit a few sentences
    src = pad_batch([ds[i][0] for i in range(16)], cfg.pad_id, 16)
    trg = pad_batch([ds[i][1] for i in range(16)], cfg.pad_id, 16)
    tgt_in = paddle.to_tensor(trg[:, :-1])
    labels = paddle.to_tensor(trg[:, 1:])
    src_t = paddle.to_tensor(src)
    for step in range(30):
        loss = model(src_t, tgt_in, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 10 == 0:
            print(f"step {step} loss {float(loss):.4f}")

    out = model.generate(src_t[:4], max_length=12)
    print("src :", src[0][:10])
    print("pred:", np.asarray(out.numpy())[0])
    print("ref :", trg[0][:12])


if __name__ == "__main__":
    main()
