"""Real-chip throughput bench (SURVEY §6 / BASELINE.json configs).

Stdout contract — LAST JSON line wins: the orchestrator streams an
updated snapshot line every time a result lands on disk (and on
SIGTERM/atexit), then one final line at the natural end; the driver
records the stdout tail and parses the last parseable line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...details}
A mid-run kill therefore still leaves the latest partials in the tail
(r04 lost a successful probe to an empty tail; this is the fix).

Headline metric: BERT-base MLM tokens/sec/chip (AMP O2 bf16, whole-step
jit with donated buffers); falls back to ResNet50 imgs/sec then LeNet
imgs/sec if the headline config never produced a number.

Process architecture — a CAMPAIGN of per-config children behind one
patient probe (ROADMAP item 4; supersedes the single-runner design
once the round-4 grant-queue rules were folded in). The axon pool
grants the chip to ONE client session at a time, and a client killed
while WAITING leaves an unclaimed grant that must time out upstream
before the next waiter is served — so kill-safety is decided by where
a child is in its lifecycle, not by impatience:
  * the ORCHESTRATOR (plain `python bench.py`) never imports jax;
  * it first spawns ONE patient PROBE child (backend liveness); the
    probe is NEVER killed early — orphaned at the global deadline at
    worst (killing a grant-waiter poisons the queue, the r03/r04
    wedge);
  * then every config runs in its OWN child process, CHEAPEST FIRST,
    with a per-config deadline (cost estimate + 600s compile slack)
    that starts counting only when the child writes its `.started`
    marker — the moment its backend answered, i.e. the grant is held.
    A started child that overruns is killed safely (its session dies
    with it, freeing the chip); an unstarted child on a TPU backend is
    never killed (it is a grant-waiter), while off-TPU a child that
    cannot init its backend in 600s is wedged and killed. One hung or
    crashing config can no longer zero out a round;
  * children share the compile-cache dir, so later configs load the
    executables earlier ones compiled; each child writes its result
    file as it finishes, and a crashing child (nonzero exit) is
    recorded and never retried;
  * the orchestrator exits NONZERO when no headline number was
    measured, so a failed bench is failure-shaped to the driver.

Child modes: `bench.py --campaign-config NAME --out-dir D` (one
campaign unit: started-marker, error capture, compile/dispatch deltas),
`bench.py --probe --out F` / `bench.py --config NAME --out F [--small]`
(manual single-shot debugging; each is a fresh session — avoid while
another client is waiting).
"""
from __future__ import annotations

import argparse
import atexit
import itertools
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__)) or "."

import numpy as np  # stdlib-adjacent; safe in the orchestrator


def _sync(x):
    """Force materialization: np.asarray round-trips through the host, the
    only sync the axon tunnel honors (block_until_ready returns early)."""
    import jax

    return np.asarray(jax.tree_util.tree_leaves(x)[0])


# peak dense bf16 FLOP/s per chip, by device_kind substring (public specs)
_PEAK_BF16 = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _chip_peak_flops():
    """Peak bf16 FLOP/s of the attached chip, or None when the device kind
    is not a known TPU (an 'MFU' against a guessed peak is noise)."""
    import jax

    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return None
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return 197e12 if "tpu" in kind else None  # v5e = BASELINE north star


# --------------------------------------------------------------------------
# bench configs (run in child processes only — all jax imports are local)
# --------------------------------------------------------------------------

def _step_flops(compiled, params, batch, seq):
    """(flops_xla or None, flops_analytic): XLA cost analysis alongside
    the analytic transformer estimate 6*params*tokens — BOTH are
    recorded so the fallback's error vs the real compile is measurable
    (round-5 verdict #5); the tunnel backend may not expose cost
    analysis, in which case only the analytic number exists."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0)) if cost else 0.0
    except Exception:  # noqa: BLE001 — cost analysis optional per backend
        flops = 0.0
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    return (flops if flops > 0 else None), 6.0 * n_params * batch * seq


def _mfu_fields(prefix, flops_xla, flops_analytic, step_s):
    """MFU from both FLOP sources + their ratio, against the chip peak."""
    peak = _chip_peak_flops()
    out = {}
    if not peak or step_s <= 0:
        return out
    out[prefix + "_mfu_analytic"] = flops_analytic / step_s / peak
    if flops_xla:
        out[prefix + "_mfu_xla"] = flops_xla / step_s / peak
        out[prefix + "_flops_xla_vs_analytic"] = flops_xla / flops_analytic
    out[prefix + "_mfu"] = out.get(prefix + "_mfu_xla",
                                   out[prefix + "_mfu_analytic"])
    out[prefix + "_mfu_source"] = "xla" if flops_xla else "analytic"
    return out


def bench_bert(batch=32, seq=128, steps=30, warmup=5):
    """BERT-base MLM, AMP O2 (bf16 weights, f32 norms), fused jitted step.
    batch 32 (not 16): 2048-token steps underfeed the MXU — the v5e HBM
    comfortably holds batch 32 with Adam state, and tokens/sec is the
    headline."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(dropout=0.0, attention_dropout=0.0)  # base config
    model = BertForMaskedLM(cfg)
    paddle.amp.decorate(model, level="O2")  # bf16 weights, norms f32
    model.eval()  # dropout off; stats frozen (MLM has no BN)

    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            # tape off: jax.value_and_grad is the single AD level (the
            # eager tape nesting inside it would second-differentiate the
            # Pallas custom_vjp forward — same pattern as hapi/model.py)
            with paddle.no_grad():
                out, _ = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, None, Tensor(labels))
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st, jnp.float32(1e-4),
                                             meta=meta)
        return new_p, new_s, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    lowered = jit_step.lower(params, states, ids, labels)
    # f64 scan on the LOCAL pre-optimization MLIR: fetching the optimized
    # HLO text of a whole BERT train step back through the tunnel is
    # hundreds of MB and dwarfs the compile itself. Scalar tensor<f64>
    # literals (weak-typed python floats under x64, converted in place)
    # are free; SHAPED f64 arrays are the perf cliff.
    import re

    # any shaped tensor (static `2x...` or dynamic `?x...`) ends in `xf64`
    mlir_text = lowered.as_text()
    f64_free = not re.search(r"tensor<[^>]*xf64>", mlir_text)
    # proof the Pallas flash kernel ENGAGES in the headline config when
    # lowered for TPU (dispatch requires backend=="tpu"; on CPU this is
    # expected False) — round-5 verdict #9's HLO evidence, recorded in
    # the bench JSON whenever the chip lowers the step
    flash_in_hlo = bool(re.search(r"tpu_custom_call|mosaic", mlir_text))
    compiled = lowered.compile()
    flops_xla, flops_analytic = _step_flops(compiled, params, batch, seq)

    for _ in range(warmup):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss if warmup else params)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    dt = time.perf_counter() - t0
    out = {
        "bert_tokens_per_sec": steps * batch * seq / dt,
        "bert_step_ms": dt / steps * 1e3,
        "bert_loss": float(loss),
        "f64_free": f64_free,
        "bert_flash_in_hlo": flash_in_hlo,
    }
    out.update(_mfu_fields("bert", flops_xla, flops_analytic, dt / steps))
    return out


def bench_gpt(batch=8, seq=512, steps=20, warmup=3):
    """GPT-2 small causal-LM train step (bf16 weights, donated buffers) —
    the single-chip slice of the BASELINE 'GPT-2 sharding+PP' config."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position=seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    paddle.amp.decorate(model, level="O2")
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            with paddle.no_grad():
                out = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, Tensor(labels))[0]
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st,
                                             jnp.float32(1e-4), meta=meta)
        return new_p, new_s, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    compiled = jit_step.lower(params, states, ids, labels).compile()
    flops_xla, flops_analytic = _step_flops(compiled, params, batch, seq)
    for _ in range(warmup):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    dt = time.perf_counter() - t0
    out = {"gpt_tokens_per_sec": steps * batch * seq / dt,
           "gpt_step_ms": dt / steps * 1e3,
           "gpt_loss": float(loss)}
    out.update(_mfu_fields("gpt", flops_xla, flops_analytic, dt / steps))
    return out


def bench_resnet50(batch=64, steps=20, warmup=3):
    """ResNet50 static-graph Executor (single-device fp32)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [batch, 3, 224, 224], "float32")
            y = paddle.static.data("y", [batch], "int64")
            logits = resnet50(num_classes=100)(x)
            loss = nn.functional.cross_entropy(logits, y)
            paddle.optimizer.Momentum(learning_rate=0.1,
                                      momentum=0.9).minimize(loss)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        # device-resident feeds: measure the train step, not the tunnel's
        # host->device bandwidth (input overlap is bench_dataloader's job)
        from paddle_tpu.core.tensor import Tensor as _T

        xs = _T(rng.randn(batch, 3, 224, 224).astype(np.float32))
        ys = _T(rng.randint(0, 100, batch).astype(np.int64))
        for _ in range(warmup):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
        dt = time.perf_counter() - t0
    finally:
        paddle.disable_static()
    return {"resnet50_imgs_per_sec": steps * batch / dt,
            "resnet50_step_ms": dt / steps * 1e3}


def _span_phases(tracing_mod, fn, keys=None):
    """Per-phase wall-clock decomposition of one extra UNTIMED pass of
    `fn` under the span tracer (runtime/tracing.py): tracing adds
    overhead, so it must never touch the A/B numbers — the timed arms
    run with the tracer off, then this pass runs the same loop traced
    and reads the phase totals. Default keys are the train-step
    perf-trajectory contract (data/forward/backward/optimizer/flush
    seconds); `keys` maps output key -> span category for workloads
    that decompose differently (the serve bench)."""
    import tempfile

    # respect an operator-configured tracer (PADDLE_TPU_TRACE): reuse
    # it rather than hijacking the process-wide trace file mid-run; a
    # throwaway dir (and the post-pass disable) only when bench itself
    # turned tracing on
    already = tracing_mod.enabled()
    if not already:
        tracing_mod.configure(tempfile.mkdtemp(prefix="bench_trace_"))
    tracing_mod.reset_span_stats()
    try:
        fn()
    finally:
        if not already:
            tracing_mod.set_enabled(False)
    ph = tracing_mod.phase_totals()
    if keys is not None:
        return {k: round(ph.get(cat, 0.0), 6) for k, cat in keys.items()}
    return {
        # "data" = the fit-level data_wait span, which already covers
        # the loader's io spans (queue wait / unstage) in full — adding
        # the io cat would double count; it is only the fallback for
        # workloads that drive the loader without Model.fit
        "data": round(ph.get("data", 0.0) or ph.get("io", 0.0), 6),
        "forward": round(ph.get("forward", 0.0), 6),
        "backward": round(ph.get("backward", 0.0), 6),
        "optimizer": round(ph.get("optimizer", 0.0), 6),
        "flush": round(ph.get("fusion", 0.0), 6),
    }


def bench_eager_dispatch(iters=100, batch=32, in_dim=64, hidden=128,
                         out_dim=8, warmup=5):
    """Eager-op dispatch microbench (CPU-runnable): a small-MLP eager
    train step (plain dygraph, NO to_static / hapi fusion — exactly the
    path jit.to_static can't reach) with the jit-cached dispatcher ON
    vs OFF (PADDLE_TPU_EAGER_JIT bypass), plus the cache hit rate after
    warmup. Pinned to the CPU backend so the bench trajectory records a
    real number even when the TPU tunnel is dead — every op here is
    byte-identical XLA either way, only the dispatch layer differs."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as PF
    from paddle_tpu.core import dispatch
    from paddle_tpu.core.tensor import Tensor as _T
    from paddle_tpu.runtime import tracing as _tracing

    rng = np.random.RandomState(0)
    res = {}
    with jax.default_device(jax.devices("cpu")[0]):
        x = _T(rng.randn(batch, in_dim).astype(np.float32))
        y = _T(rng.randn(batch, out_dim).astype(np.float32))

        def make_params():
            return [
                _T(rng.randn(in_dim, hidden).astype(np.float32) * 0.1,
                   stop_gradient=False),
                _T(np.zeros(hidden, np.float32), stop_gradient=False),
                _T(rng.randn(hidden, out_dim).astype(np.float32) * 0.1,
                   stop_gradient=False),
                _T(np.zeros(out_dim, np.float32), stop_gradient=False),
            ]

        def run_loop(n, params, opt):
            for _ in range(n):
                # the forward span (library spans cover backward /
                # optimizer / flush) — a shared no-op object while
                # tracing is off, so the timed arms pay ~nothing
                with _tracing.span("forward", "forward"):
                    h = PF.relu(paddle.matmul(x, params[0]) + params[1])
                    p = paddle.matmul(h, params[2]) + params[3]
                    loss = ((p - y) * (p - y)).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            _sync(loss._value)
            return loss

        def timed(flag):
            prev = dispatch.set_eager_jit(flag)
            try:
                params = make_params()
                opt = paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=params)
                run_loop(warmup, params, opt)
                dispatch.reset_dispatch_stats()
                t0 = time.perf_counter()
                run_loop(iters, params, opt)
                dt = time.perf_counter() - t0
                return dt, dispatch.dispatch_stats()
            finally:
                dispatch.set_eager_jit(prev)

        dt_on, stats_on = timed(True)
        dt_off, stats_off = timed(False)

        def _phase_pass():
            params = make_params()
            opt = paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=params)
            run_loop(min(iters, 20), params, opt)

        res["eager_dispatch_phase_s"] = _span_phases(_tracing, _phase_pass)

    fwd = stats_on["forward"]
    n_ops = fwd["hits"] + fwd["misses"]
    res["eager_dispatch_steps_per_sec"] = iters / dt_on
    res["eager_dispatch_baseline_steps_per_sec"] = iters / dt_off
    res["eager_dispatch_speedup"] = dt_off / dt_on
    res["eager_dispatch_hit_rate"] = fwd["hit_rate"]
    res["eager_dispatch_ops_per_sec"] = (n_ops / dt_on) if n_ops else None
    res["eager_dispatch_bypassed_ops"] = (
        stats_off["forward"]["bypasses"])
    return res


def bench_eager_fusion(iters=100, batch=32, in_dim=64, hidden=128,
                       out_dim=8, warmup=5):
    """Trace-fusion microbench (CPU-runnable): the SAME small-MLP eager
    train step as `eager_dispatch`, with trace fusion (core/fusion.py)
    ON vs OFF. OFF is today's per-op jit-cached dispatch, so the A/B
    isolates exactly what deferred execution buys: op-boundary dispatch
    overhead removed and XLA fusing across the whole fwd+bwd run,
    flushed as one program per step at the optimizer boundary."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as PF
    from paddle_tpu.core import dispatch, fusion
    from paddle_tpu.core.tensor import Tensor as _T
    from paddle_tpu.runtime import tracing as _tracing

    rng = np.random.RandomState(0)
    res = {}
    with jax.default_device(jax.devices("cpu")[0]):
        x = _T(rng.randn(batch, in_dim).astype(np.float32))
        y = _T(rng.randn(batch, out_dim).astype(np.float32))

        def make_params():
            # a FRESH stream per arm: both arms must start from
            # identical params — the A/B also asserts numerical parity
            prng = np.random.RandomState(1)
            return [
                _T(prng.randn(in_dim, hidden).astype(np.float32) * 0.1,
                   stop_gradient=False),
                _T(np.zeros(hidden, np.float32), stop_gradient=False),
                _T(prng.randn(hidden, out_dim).astype(np.float32) * 0.1,
                   stop_gradient=False),
                _T(np.zeros(out_dim, np.float32), stop_gradient=False),
            ]

        def run_loop(n, params, opt):
            for _ in range(n):
                with _tracing.span("forward", "forward"):
                    h = PF.relu(paddle.matmul(x, params[0]) + params[1])
                    p = paddle.matmul(h, params[2]) + params[3]
                    loss = ((p - y) * (p - y)).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            _sync(loss._value)  # np round trip = fusion flush point
            return loss

        def one_rep(flag):
            prev = fusion.set_fusion(flag)
            try:
                params = make_params()
                opt = paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=params)
                run_loop(warmup, params, opt)
                t0 = time.perf_counter()
                loss = run_loop(iters, params, opt)
                return time.perf_counter() - t0, float(loss._value)
            finally:
                fusion.set_fusion(prev)

        # interleaved best-of-2 per arm: host-load drift on a shared
        # CPU box otherwise biases whichever arm happens to run last
        one_rep(False), one_rep(True)  # shared warm pass (compiles)
        dispatch.reset_dispatch_stats()
        dt_off, loss_off = one_rep(False)
        dt_on, loss_on = one_rep(True)
        stats_on = dispatch.dispatch_stats()
        d2_off, _ = one_rep(False)
        d2_on, _ = one_rep(True)
        dt_off, dt_on = min(dt_off, d2_off), min(dt_on, d2_on)

        def _phase_pass():
            prev = fusion.set_fusion(True)
            try:
                params = make_params()
                opt = paddle.optimizer.SGD(learning_rate=0.01,
                                           parameters=params)
                run_loop(min(iters, 20), params, opt)
            finally:
                fusion.set_fusion(prev)

        # per-phase step-time decomposition UNDER FUSION: forward/
        # backward here are recording time; "flush" is where the fused
        # program actually executes — exactly the split the timeline
        # exists to show
        res["eager_fusion_phase_s"] = _span_phases(_tracing, _phase_pass)

    fus = stats_on["fusion"]
    n_flush = sum(fus["flushes"].values())
    res["eager_fusion_steps_per_sec"] = iters / dt_on
    res["eager_fusion_baseline_steps_per_sec"] = iters / dt_off
    res["eager_fusion_speedup"] = dt_off / dt_on
    res["eager_fusion_flushes"] = n_flush
    res["eager_fusion_avg_trace_len"] = fus["avg_trace_len"]
    res["eager_fusion_fused_hit_rate"] = fus["fused"]["hit_rate"]
    res["eager_fusion_fallbacks"] = fus["fallbacks"]
    # numerics must match the per-op path to allclose tolerance — a
    # fused win with wrong math is not a win
    res["eager_fusion_loss_matches"] = bool(
        np.allclose(loss_on, loss_off, rtol=1e-5, atol=1e-6))
    return res


def bench_lenet(batch=256, steps=30, warmup=3):
    """LeNet dygraph Model.fit path (whole-step-jitted train_batch)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(parameters=model.parameters()),
                  nn.CrossEntropyLoss())
    from paddle_tpu.core.tensor import Tensor as _T

    rng = np.random.RandomState(0)
    xs = _T(rng.randn(batch, 1, 28, 28).astype(np.float32))
    ys = _T(rng.randint(0, 10, (batch, 1)).astype(np.int64))
    for _ in range(warmup):
        model.train_batch([xs], [ys])
    t0 = time.perf_counter()
    for _ in range(steps):
        model.train_batch([xs], [ys])
    dt = time.perf_counter() - t0
    return {"lenet_imgs_per_sec": steps * batch / dt}


def bench_generate(batches=(1, 8), prompt=32, new_tokens=96,
                   eager_tokens=8):
    """Jitted static-KV decode throughput (GPT-2 small, greedy) at batch
    1 and 8 with a prefill/decode split, vs a naive eager re-forward
    decode — the A/B that justifies the prefill/decode executables
    (models/gpt.py). The split: a max_new_tokens=1 run times
    prefill(+1 step); subtracting it from the full run isolates the
    per-token decode cost."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(max_position=prompt + new_tokens,
                                     dropout=0.0))
    paddle.amp.decorate(model, level="O2")
    model.eval()
    rng = np.random.RandomState(0)
    res = {}
    batches = tuple(batches)
    for bsz in batches:
        ids = paddle.to_tensor(rng.randint(0, 50304, (bsz, prompt)))
        out = model.generate(ids, max_new_tokens=new_tokens)  # compile
        _sync(out._value)
        o1 = model.generate(ids, max_new_tokens=1)  # compile short arm
        _sync(o1._value)
        t0 = time.perf_counter()
        o1 = model.generate(ids, max_new_tokens=1)
        _sync(o1._value)
        t_one = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=new_tokens)
        _sync(out._value)
        t_full = time.perf_counter() - t0
        res[f"decode_b{bsz}_prefill_ms"] = t_one * 1e3  # prefill + 1 step
        if new_tokens > 1:
            per_tok = max(t_full - t_one, 1e-9) / (new_tokens - 1)
            res[f"decode_b{bsz}_ms_per_token"] = per_tok * 1e3
            res[f"decode_b{bsz}_tokens_per_sec"] = bsz / per_tok
    if not batches:
        return res
    # legacy keys = the largest batch's steady-state decode rate
    # (prefill excluded — the split keys above carry it); only present
    # when the split keys exist (new_tokens > 1)
    batch = batches[-1]
    if f"decode_b{batch}_tokens_per_sec" in res:
        res["decode_tokens_per_sec"] = res[f"decode_b{batch}_tokens_per_sec"]
        res["decode_ms_per_token"] = res[f"decode_b{batch}_ms_per_token"]
    ids = paddle.to_tensor(rng.randint(0, 50304, (batch, prompt)))

    # eager baseline: full re-forward per token, no KV cache, argmax on
    # host — what generate() would cost without the static-KV design.
    # Kept to a few tokens; per-token cost is flat enough to compare.
    try:
        cur = ids
        with paddle.no_grad():
            logits = model(cur)  # warm the [batch, prompt] forward
            _sync(logits._value)
            t0 = time.perf_counter()
            for _ in range(eager_tokens):
                logits = model(cur)
                nxt = jnp.argmax(logits._value[:, -1, :], axis=-1)
                cur = paddle.concat(
                    [cur, paddle.to_tensor(np.asarray(nxt))[:, None]],
                    axis=1)
            _sync(cur._value)
        res["decode_eager_ms_per_token"] = (
            (time.perf_counter() - t0) / eager_tokens * 1e3)
    except Exception as e:  # noqa: BLE001 — the A/B arm must not kill decode
        res["decode_eager_error"] = str(e)[:200]
    return res


def bench_flash_attention(batch=4, heads=12, seq=1024, dim=64, iters=50):
    """Pallas flash attention vs XLA softmax attention, fwd+bwd."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    rng = np.random.RandomState(0)
    shape = (batch * heads, seq, dim)
    q, k, v = (jnp.asarray(rng.randn(*shape).astype(np.float32))
               for _ in range(3))

    def xla_loss(q, k, v):
        out, _ = _xla_attention(q[None], k[None], v[None], None, 0.0, None,
                                True)
        return (out ** 2).mean()

    def flash_loss(q, k, v):
        return (flash_attention_raw(q, k, v, True) ** 2).mean()

    res = {}
    arms = [("xla", xla_loss, jnp.float32),
            ("flash", flash_loss, jnp.float32),
            # bf16 arms: the dtype real training runs in on the MXU
            ("xla_bf16", xla_loss, jnp.bfloat16),
            ("flash_bf16", flash_loss, jnp.bfloat16)]
    for name, fn, dt in arms:
        try:
            qq, kk, vv = (x.astype(dt) for x in (q, k, v))
            g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
            _sync(g(qq, kk, vv))
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(qq, kk, vv)
            _sync(out)
            res[f"attn_{name}_ms"] = (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:  # noqa: BLE001
            res[f"attn_{name}_ms"] = None
            res[f"attn_{name}_error"] = str(e)[:200]
    return res


def bench_blockwise_ce(n=4096, hidden=768, vocab=50304, iters=20):
    """Blockwise fused LM-head CE vs materialized-logits CE, fwd+bwd —
    the HBM-bandwidth lever behind ops/blockwise_ce.py."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.blockwise_ce import blockwise_softmax_ce

    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(n, hidden).astype(np.float32) * 0.02)
    w = jnp.asarray(rng.randn(vocab, hidden).astype(np.float32) * 0.02)
    y = jnp.asarray(rng.randint(0, vocab, n))

    def naive(h, w):
        logits = h @ w.T
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        return (logz - jnp.take_along_axis(logits, y[:, None],
                                           axis=-1)[:, 0]).mean()

    def fused(h, w):
        return blockwise_softmax_ce(h, w, y)

    res = {}
    for name, fn in [("naive", naive), ("blockwise", fused)]:
        try:
            g = jax.jit(jax.grad(fn, argnums=(0, 1)))
            _sync(g(h, w))
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(h, w)
            _sync(out)
            res[f"ce_{name}_ms"] = (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:  # noqa: BLE001
            res[f"ce_{name}_ms"] = None
            res[f"ce_{name}_error"] = str(e)[:200]
    return res


def bench_int8(m=4096, k=4096, n=4096, iters=30):
    """int8 MXU vs bf16 matmul throughput (v5e: 394 int8 TOPS vs 197
    bf16 TFLOPS) — the execution lever behind paddle.quantization's
    int8 layers (quantization/layers.py int8 dot_general)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a8 = jnp.asarray(rng.randint(-127, 127, (m, k), dtype=np.int8))
    b8 = jnp.asarray(rng.randint(-127, 127, (k, n), dtype=np.int8))
    abf = jnp.asarray(rng.randn(m, k).astype(np.float32), jnp.bfloat16)
    bbf = jnp.asarray(rng.randn(k, n).astype(np.float32), jnp.bfloat16)

    @jax.jit
    def mm_int8(a, b):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    @jax.jit
    def mm_bf16(a, b):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    res = {}
    flops = 2.0 * m * k * n
    for name, fn, x, y in [("int8", mm_int8, a8, b8),
                           ("bf16", mm_bf16, abf, bbf)]:
        try:
            _sync(fn(x, y))
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(x, y)
            _sync(out)
            dt = (time.perf_counter() - t0) / iters
            res[f"matmul_{name}_tops"] = flops / dt / 1e12
        except Exception as e:  # noqa: BLE001 — one arm must not kill the A/B
            res[f"matmul_{name}_tops"] = None
            res[f"matmul_{name}_error"] = str(e)[:200]
    return res


def bench_dataloader(n=512, batch=64, shape=(3, 224, 224), epochs=3):
    """Input pipeline A/B: thread-prefetch DataLoader vs the C++ staging
    ring (csrc/staging_pool.cpp) — imgs/sec of collate+transfer."""
    import paddle_tpu as paddle

    class SynthDataset(paddle.io.Dataset):
        rng = np.random.RandomState(0)
        base = rng.randn(32, *shape).astype(np.float32)

        def __len__(self):
            return n

        def __getitem__(self, i):
            # simulate decode/augment work: flip + normalize
            img = self.base[i % 32]
            img = img[..., ::-1] * (1.0 / 255.0) - 0.5
            return np.ascontiguousarray(img), np.int64(i % 10)

    res = {}
    for name, kw in [("threads", {}), ("staging", {"use_staging_pool": True})]:
        loader = paddle.io.DataLoader(SynthDataset(), batch_size=batch,
                                      num_workers=4, **kw)
        for x, _ in loader:  # warm (compile/allocate/pool build)
            pass
        t0 = time.perf_counter()
        count = 0
        for _ in range(epochs):
            for x, _ in loader:
                count += int(x.shape[0])
        _sync(x._value)
        res[f"dataloader_{name}_imgs_per_sec"] = count / (
            time.perf_counter() - t0)
    return res


def bench_input_pipeline(n=256, batch=16, feat=64, hidden=768,
                         delay_ms=3.0, reps=2):
    """Input-pipeline A/B on a DATA-BOUND workload (CPU-runnable): a
    throttled synthetic dataset (a fixed per-batch host delay models
    decode/augment/IO cost) driven through `Model.fit`, synchronous
    `next()` vs the `DevicePrefetcher` double-buffered device staging
    (io/prefetch.py). Arms run interleaved best-of-N so ambient noise
    hits both equally. Persists the data-wait SHARE of step time per
    arm (the `paddle_tpu_data_wait_seconds` histogram the win was
    instrumented for), the h2d/overlap counters, loss-trajectory
    bit-equality, and a `*_phase_s` span decomposition of the prefetch
    arm. Pinned to the CPU backend (the contended resource here is the
    HOST, and the number must land even on a dead TPU tunnel)."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.io import prefetch as _prefetch
    from paddle_tpu.runtime import telemetry as _telemetry
    from paddle_tpu.runtime import tracing as _tracing

    per_item = delay_ms * 1e-3 / batch

    class Throttled(paddle.io.Dataset):
        rng = np.random.RandomState(0)
        xs = rng.rand(n, feat).astype(np.float32)
        ys = rng.rand(n, 1).astype(np.float32)

        def __len__(self):
            return n

        def __getitem__(self, i):
            time.sleep(per_item)  # the modeled host-side per-item cost
            return self.xs[i], self.ys[i]

    def _mk_model():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(feat, hidden), nn.Tanh(),
                            nn.Linear(hidden, hidden), nn.Tanh(),
                            nn.Linear(hidden, 1))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(0.01,
                                           parameters=net.parameters()),
                      nn.MSELoss())
        return model

    def _hist_sum(name):
        fam = _telemetry.snapshot().get(name) or {}
        series = fam.get("series") or [{}]
        return float(series[0].get("sum", 0.0))

    ds = Throttled()

    def run_arm(prefetch_on):
        model = _mk_model()
        losses = []

        class _Rec(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                losses.append(logs["loss"])

        dw0 = _hist_sum("paddle_tpu_data_wait_seconds")
        h0 = _hist_sum("paddle_tpu_h2d_seconds")
        t0 = time.perf_counter()
        model.fit(ds, epochs=1, batch_size=batch, shuffle=False,
                  verbose=0, prefetch=prefetch_on, callbacks=[_Rec()])
        dt = time.perf_counter() - t0
        return {"wall_s": dt,
                "data_wait_s": _hist_sum(
                    "paddle_tpu_data_wait_seconds") - dw0,
                "h2d_s": _hist_sum("paddle_tpu_h2d_seconds") - h0,
                "losses": losses}

    best = {}
    loss_traces = {}
    # PROCESS-wide CPU pin (jax.config, not the thread-local
    # jax.default_device context): the DevicePrefetcher commits batches
    # on its own producer thread, which a with-block would never cover —
    # on a live-TPU host that thread would otherwise commit to TPU
    # against CPU-resident params
    prev_dev = jax.config.jax_default_device
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    try:
        run_arm(False)  # warm: compile the fused step, outside the A/B
        for _rep in range(max(1, reps)):
            for arm, flag in (("sync", False), ("prefetch", True)):
                r = run_arm(flag)
                loss_traces.setdefault(arm, r["losses"])
                if arm not in best or r["wall_s"] < best[arm]["wall_s"]:
                    best[arm] = r

        def _phase_pass():
            run_arm(True)

        phase_s = _span_phases(_tracing, _phase_pass)
    finally:
        jax.config.update("jax_default_device", prev_dev)

    steps = (n + batch - 1) // batch
    res = {}
    for arm in ("sync", "prefetch"):
        b = best[arm]
        res[f"input_pipeline_{arm}_steps_per_sec"] = steps / b["wall_s"]
        res[f"input_pipeline_{arm}_data_wait_s"] = round(
            b["data_wait_s"], 6)
        res[f"input_pipeline_{arm}_data_wait_share"] = round(
            b["data_wait_s"] / b["wall_s"], 6)
        res[f"input_pipeline_{arm}_h2d_s"] = round(b["h2d_s"], 6)
    res["input_pipeline_speedup"] = (best["sync"]["wall_s"]
                                     / best["prefetch"]["wall_s"])
    sync_share = res["input_pipeline_sync_data_wait_share"]
    pf_share = res["input_pipeline_prefetch_data_wait_share"]
    res["input_pipeline_data_wait_cut"] = (
        sync_share / pf_share if pf_share > 0 else None)
    res["input_pipeline_loss_bit_exact"] = (
        loss_traces["sync"] == loss_traces["prefetch"])
    st = _prefetch.prefetch_stats()
    res["input_pipeline_overlap_ratio"] = st["overlap_ratio"]
    res["input_pipeline_prefetch_stalls"] = st["stalls"]
    res["input_pipeline_phase_s"] = phase_s
    return res


def bench_bert_b64(batch=64, seq=128, steps=30, warmup=5):
    """Batch-scaling A/B of the headline: PERF_ESTIMATES puts b32/s128
    at arithmetic intensity ~45 FLOP/byte (bandwidth-leaning on v5e);
    b64 doubles compute against near-constant parameter traffic. The
    headline stays b32 for cross-round comparability; keys here are
    b64_-prefixed so the merge cannot overwrite the headline's."""
    return {"b64_" + k: v for k, v in
            bench_bert(batch, seq, steps, warmup).items()}


def bench_tpu_correctness(**kw):
    """On-device correctness for the perf-path kernels (flash fwd/bwd,
    tilings, ring attention, blockwise CE, int8 MXU) vs host float64 /
    on-device XLA oracles — the hardware evidence the CPU/interpret
    tests cannot give (paddle_tpu/testing/tpu_checks.py; also exposed
    as the @pytest.mark.tpu suite)."""
    from paddle_tpu.testing.tpu_checks import run_tpu_checks

    return run_tpu_checks(**kw)


def bench_flash_tiling(batch=4, heads=12, dim=64, seqs=(512, 2048),
                       blocks=(128, 256, 512), iters=20):
    """Flash-attention block-tiling sweep, bf16 fwd+bwd — picks the
    measured per-seq winner so dispatch defaults come from data, not
    guesses (round-5 verdict #4). Exactness across these tilings is
    already pinned by tests; this measures them."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    rng = np.random.RandomState(0)
    res = {}
    for seq in seqs:
        q, k, v = (jnp.asarray(rng.randn(batch * heads, seq, dim)
                               .astype(np.float32), jnp.bfloat16)
                   for _ in range(3))
        best = None
        for bq in blocks:
            for bk in blocks:
                if seq % bq or seq % bk:
                    continue

                def loss(qq, kk, vv, bq=bq, bk=bk):
                    o = flash_attention_raw(qq, kk, vv, True,
                                            block_q=bq, block_k=bk)
                    return (o.astype(jnp.float32) ** 2).mean()

                key = f"tiling_s{seq}_q{bq}_k{bk}"
                try:
                    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                    _sync(g(q, k, v))
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        out = g(q, k, v)
                    _sync(out)
                    ms = (time.perf_counter() - t0) / iters * 1e3
                    res[key + "_ms"] = ms
                    if best is None or ms < best[0]:
                        best = (ms, bq, bk)
                except Exception as e:  # noqa: BLE001 — sweep continues
                    res[key + "_error"] = str(e)[:160]
        if best is not None:
            res[f"tiling_s{seq}_best"] = f"q{best[1]}_k{best[2]}"
            res[f"tiling_s{seq}_best_ms"] = best[0]
    return res


def bench_tpu_trace(batch=32, seq=128, steps=3):
    """Real on-chip profiler trace of the BERT step (perfetto/xplane
    under profiler_log/) — on-hardware scheduling evidence for the perf
    levers. Runs LAST: if the tunnel's profiler wedges, everything
    already measured is safe on disk, and the persistent compile cache
    makes the re-compile of the bert step a cache hit."""
    import jax

    if jax.default_backend() != "tpu":
        return {"tpu_trace_skipped": "not on tpu"}
    logdir = os.path.join(REPO, "profiler_log",
                          time.strftime("bench_%Y%m%d_%H%M%S"))
    with jax.profiler.trace(logdir):
        res = bench_bert(batch, seq, steps=steps, warmup=1)
    return {"tpu_trace_dir": logdir,
            "tpu_trace_step_ms": res.get("bert_step_ms")}


def bench_serve_decode(requests=8, prompt=8, new_tokens=16, max_running=4,
                       token_budget=8):
    """Serving-engine decode-loop bench (CPU-runnable): N concurrent
    requests through the continuous-batching engine (paged KV cache +
    ragged attention, paddle_tpu/inference/). Reports generated
    tokens/sec plus per-request latency percentiles — the serving
    sibling of the eager_dispatch/eager_fusion train-step numbers — and
    a `*_phase_s` span decomposition (serve step loop / dispatched op
    runtime / fusion flush) from an extra untimed traced pass."""
    import jax

    from paddle_tpu.inference import (ServeConfig, ServingEngine,
                                      TinyServeModel)
    from paddle_tpu.runtime import tracing as _tracing

    def mk():
        model = TinyServeModel(vocab=128, dim=32, layers=2, heads=4,
                               ffn=64, seed=0)
        return ServingEngine(model, ServeConfig(
            max_running=max_running, token_budget=token_budget,
            block_size=8, num_blocks=128, max_blocks_per_seq=16))

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, size=prompt).tolist()
               for _ in range(requests)]
    res = {}
    with jax.default_device(jax.devices("cpu")[0]):
        mk().generate(prompts[:2], max_new_tokens=2)  # warm compiles
        eng = mk()
        for p in prompts:
            eng.submit(p, max_new_tokens=new_tokens)
        t0 = time.perf_counter()
        eng.run()
        dt = time.perf_counter() - t0
        st = eng.stats()
        lat = sorted(r.t_done - r.t_submit
                     for r in eng.scheduler.finished)
        res["serve_decode_tokens_per_sec"] = st["tokens_out"] / dt
        res["serve_decode_steps_per_sec"] = st["steps"] / dt
        res["serve_decode_requests"] = len(lat)
        res["serve_decode_p50_ms"] = (
            float(np.percentile(lat, 50)) * 1e3 if lat else None)
        res["serve_decode_p99_ms"] = (
            float(np.percentile(lat, 99)) * 1e3 if lat else None)
        res["serve_decode_kv_highwater_blocks"] = st["kv"]["highwater"]

        # SLO keys from the ISSUE-18 open-loop harness: a short
        # Poisson-arrival pass on a fresh engine gives the serving
        # numbers the chaos gate tracks — TTFT percentiles, shed rate
        # under the admission bounds, and goodput (completed-request
        # tokens/sec, distinct from the raw decode tokens/sec above)
        from tools.loadgen import run_load
        lg = run_load(mk(), rate_rps=2.0 * max(res.get(
            "serve_decode_requests", requests), 1),
            duration_s=1.0, prompt_lens=(prompt,),
            new_tokens=(min(new_tokens, 4),), seed=0,
            hard_wall_s=120.0)
        res["serve_decode_ttft_p50_ms"] = (
            lg["ttft_p50_s"] * 1e3 if lg["ttft_p50_s"] is not None
            else None)
        res["serve_decode_ttft_p99_ms"] = (
            lg["ttft_p99_s"] * 1e3 if lg["ttft_p99_s"] is not None
            else None)
        res["serve_decode_shed_rate"] = lg["shed_rate"]
        res["serve_decode_goodput_tokens_per_sec"] = (
            lg["goodput_tokens_per_sec"])
        # windowed quantiles (ISSUE 20): the rolling last-1m view the
        # engine's /statusz gauges publish, vs the lifetime aggregates
        # above — on a short bench pass they track each other, but the
        # key names match the SLO surface operators actually watch
        res["serve_decode_ttft_p99_ms_1m"] = (
            lg["ttft_p99_s_1m"] * 1e3
            if lg.get("ttft_p99_s_1m") is not None else None)
        res["serve_decode_goodput_tokens_per_sec_1m"] = (
            lg.get("goodput_tokens_per_sec_1m"))
        res["serve_decode_shed_rate_1m"] = lg.get("shed_rate_1m")

        def _phase_pass():
            e = mk()
            for p in prompts:
                e.submit(p, max_new_tokens=min(new_tokens, 8))
            e.run()

        # a forward-only workload decomposes differently from a train
        # step: serve = the decode loop end to end, forward = sampled
        # dispatched op runtime inside it, flush = fusion flush time
        res["serve_decode_phase_s"] = _span_phases(
            _tracing, _phase_pass,
            keys={"serve": "serve", "forward": "dispatch",
                  "flush": "fusion"})
    return res


# name -> (fn, small_kwargs, full_cost_estimate_s). Order is the RUN
# order: lenet first as a cheap sanity probe of real execution, then the
# BERT headline — with one patient runner writing results incrementally,
# landing the headline early maximizes what survives an external kill at
# an unknown deadline; the cheaper diagnostics follow.
CONFIGS = {
    # first: CPU-pinned and cheap, so the bench trajectory records a real
    # number (and the dispatch-cache hit rate) even when every TPU config
    # below errors out on a dead tunnel
    "eager_dispatch": (bench_eager_dispatch,
                       {"iters": 60, "batch": 16, "hidden": 64,
                        "warmup": 5}, 180),
    # the trace-fusion A/B over the same train step (fusion on vs
    # per-op jit): also CPU-pinned, also survives a dead tunnel
    "eager_fusion": (bench_eager_fusion,
                     {"iters": 60, "batch": 16, "hidden": 64,
                      "warmup": 5}, 180),
    # the serving tier's tokens/sec + p50/p99 trajectory (paged KV
    # cache + continuous batching): CPU-pinned like the two above
    "serve_decode": (bench_serve_decode,
                     {"requests": 4, "prompt": 4, "new_tokens": 4,
                      "token_budget": 8}, 240),
    # the async-input-pipeline A/B (sync next() vs double-buffered
    # device staging) on a deliberately data-bound workload: also
    # CPU-pinned-cheap, survives a dead tunnel
    # sized (like tools/data_smoke.py) so one step's compute covers one
    # batch's host cost — the regime where double buffering can hide
    # the input pipeline entirely
    "input_pipeline": (bench_input_pipeline,
                       {"n": 64, "batch": 8, "hidden": 512,
                        "delay_ms": 1.0, "reps": 1}, 240),
    "lenet": (bench_lenet, {"batch": 8, "steps": 2, "warmup": 1}, 420),
    "bert": (bench_bert, {"batch": 2, "seq": 32, "steps": 2, "warmup": 1},
             900),
    "tpu_correctness": (bench_tpu_correctness,
                        {"seq": 128, "dim": 64, "bh": 2, "vocab": 512,
                         "hidden": 64, "n": 64}, 600),
    "flash_tiling": (bench_flash_tiling,
                     {"batch": 1, "heads": 2, "seqs": (256,),
                      "blocks": (128, 256), "iters": 2}, 900),
    # same model/compile as bert at ~2x per-step compute, so its cost
    # estimate must not undercut bert's (the runner's small-fallback
    # compares remaining budget against it); placed after the hardware-
    # evidence configs so it cannot starve them
    "bert_b64": (bench_bert_b64,
                 {"batch": 4, "seq": 32, "steps": 2, "warmup": 1}, 950),
    "flash_attention": (bench_flash_attention,
                        {"batch": 1, "heads": 2, "seq": 128, "iters": 2},
                        600),
    "blockwise_ce": (bench_blockwise_ce,
                     {"n": 64, "hidden": 32, "vocab": 512, "iters": 2}, 480),
    "int8": (bench_int8, {"m": 256, "k": 256, "n": 256, "iters": 3}, 300),
    "dataloader": (bench_dataloader, {"n": 32, "batch": 8, "epochs": 1}, 420),
    "resnet50": (bench_resnet50, {"batch": 2, "steps": 2, "warmup": 1}, 900),
    "gpt": (bench_gpt, {"batch": 1, "seq": 32, "steps": 1, "warmup": 1},
            900),
    "generate": (bench_generate,
                 {"batches": (1,), "prompt": 4, "new_tokens": 4,
                  "eager_tokens": 2}, 700),
    "tpu_trace": (bench_tpu_trace,
                  {"batch": 2, "seq": 32, "steps": 1}, 360),
}

# test hook: BENCH_CONFIGS_MODULE names a module whose CONFIGS replaces
# the table above (inherited by runner children via the environment), so
# the orchestrator/runner machinery is testable with fast fake configs.
# A broken value must not break the one-JSON-line contract — fall back
# to the real table with a stderr note.
if os.environ.get("BENCH_CONFIGS_MODULE"):
    import importlib

    try:
        CONFIGS = importlib.import_module(
            os.environ["BENCH_CONFIGS_MODULE"]).CONFIGS
    except Exception as _e:  # noqa: BLE001
        print(f"bench: ignoring BENCH_CONFIGS_MODULE "
              f"({type(_e).__name__}: {_e})", file=sys.stderr)

_HEADLINE_CANDIDATES = [
    ("bert", "bert_tokens_per_sec",
     "BERT-base MLM tokens/sec/chip (AMP O2 bf16)", "tokens/sec"),
    ("resnet50", "resnet50_imgs_per_sec",
     "ResNet50 train imgs/sec/chip (static Executor, fp32)", "imgs/sec"),
    ("lenet", "lenet_imgs_per_sec", "LeNet Model.fit imgs/sec/chip",
     "imgs/sec"),
    # last resort — CPU-only microbench, so a dead TPU tunnel still
    # yields a measured (clearly-labeled) number instead of null
    ("eager_dispatch", "eager_dispatch_steps_per_sec",
     "eager small-MLP train steps/sec (CPU, jit-cached dispatch)",
     "steps/sec"),
]


# --------------------------------------------------------------------------
# child entry points
# --------------------------------------------------------------------------

def _child_setup_jax():
    """Compile-cache + platform config for a child process. Must run via
    jax.config.update, not env vars: the image's sitecustomize calls
    axon.register() at interpreter start, which force-sets
    jax_platforms="axon,cpu" (axon/register/ifrt.py), overriding
    JAX_PLATFORMS from the environment. BENCH_FORCE_CPU exists so the
    whole bench pipeline can be smoke-tested without a TPU.

    The cache dir comes from PADDLE_TPU_COMPILE_CACHE_DIR (defaulted
    here, exported by the runner so respawned children within a round
    share ONE warm dir — a respawn after a crash re-loads, not
    re-compiles); when a config later imports paddle_tpu, the warm-start
    subsystem (runtime/warmup.py) re-applies the same dir with its
    finer-grained knobs, so both layers agree."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE_DIR",
                                      os.path.join(REPO, ".jax_cache"))
    # exported too so the warmup auto-config that runs when a config
    # imports paddle_tpu applies the SAME threshold (its default is 0,
    # which would flood the shared dir with sub-second executables)
    min_s = os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_S",
                                  "1.0")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_s))
    jax.config.update("jax_raise_persistent_cache_errors", False)


def _write_out(out_path, payload):
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out_path)


def _run_probe(out_path):
    """Backend liveness: init PJRT AND run a real op — jax.devices() can
    succeed while the first execution hangs; only a round-tripped matmul
    proves the tunnel works."""
    _child_setup_jax()
    import jax
    import jax.numpy as jnp

    info = {"backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "device_kind": jax.devices()[0].device_kind}
    x = jnp.ones((256, 256), jnp.bfloat16)
    info["probe_matmul"] = float(np.asarray((x @ x).sum(), dtype=np.float32))
    _write_out(out_path, info)


def _run_config(name, out_path, small):
    _child_setup_jax()
    fn, small_kw, _ = CONFIGS[name]
    res = fn(**small_kw) if small else fn()
    _write_out(out_path, res)


def _compile_snapshot():
    """Warm-start compile counters (runtime/warmup.py), or None when
    paddle_tpu is not importable in this child. Import cost is paid by
    the first config anyway; errors must never fail the bench."""
    try:
        from paddle_tpu.runtime import warmup

        return warmup.compile_metrics()
    except Exception:  # noqa: BLE001
        return None


def _dispatch_snapshot():
    """dispatch_stats() for per-config op-level attribution, or None
    when paddle_tpu is not importable in this child."""
    try:
        from paddle_tpu.core import dispatch

        return dispatch.dispatch_stats()
    except Exception:  # noqa: BLE001
        return None


def _dispatch_delta(res, name, before, after):
    """Op-level evidence per config in the BENCH_*.json trajectory:
    forward hit/miss deltas (hit-rate regressions in the dispatch layer
    become visible round-over-round, not just aggregate wall clock) and
    the hottest ops with their sampled run-time attribution. A config
    that reset the counters itself (eager_dispatch) is detected by the
    stats generation (negative deltas alone would miss a reset whose
    post-reset traffic exceeds the pre-reset totals) and falls back to
    the absolute after-run numbers."""
    if not (before and after):
        return
    fwd_b, fwd_a = before["forward"], after["forward"]
    d_hits = fwd_a["hits"] - fwd_b["hits"]
    d_miss = fwd_a["misses"] - fwd_b["misses"]
    per_b = before.get("per_op") or {}
    if (before.get("stats_generation") != after.get("stats_generation")
            or d_hits < 0 or d_miss < 0):
        d_hits, d_miss, per_b = fwd_a["hits"], fwd_a["misses"], {}
    total = d_hits + d_miss

    def _delta_traffic(kv):
        # rank by THIS config's delta, not cumulative totals: counters
        # accumulate across configs in one runner process, so absolute
        # ranking would be dominated by earlier configs' traffic
        pb = per_b.get(kv[0]) or {}
        return (kv[1]["hits"] - pb.get("hits", 0)
                + kv[1]["misses"] - pb.get("misses", 0))

    top_ops = {}
    for op, s in sorted((after.get("per_op") or {}).items(),
                        key=lambda kv: -_delta_traffic(kv))[:8]:
        pb = per_b.get(op) or {}
        d = {"hits": s["hits"] - pb.get("hits", 0),
             "misses": s["misses"] - pb.get("misses", 0)}
        if d["hits"] + d["misses"] <= 0:
            continue  # no traffic from this config: not its story
        dr = s.get("run_samples", 0) - pb.get("run_samples", 0)
        if dr > 0:
            d["run_samples"] = dr
            d["run_s"] = round(s.get("run_s", 0.0)
                               - pb.get("run_s", 0.0), 5)
        top_ops[op] = d
    res[name + "_dispatch"] = {
        "fwd_hits": d_hits, "fwd_misses": d_miss,
        "hit_rate": round(d_hits / total, 4) if total else None,
        "top_ops": top_ops,
    }


def _registry_snapshot(max_series=20):
    """Compact telemetry-registry snapshot, taken ONCE per round (the
    registry is cumulative over the runner process, so per-config
    snapshots would overlap and double-count when merged — rounds, by
    contrast, are separate processes and merge cleanly with
    telemetry.merge_histograms). Series are capped per family so a
    label-heavy round cannot bloat the record."""
    try:
        from paddle_tpu.runtime import telemetry

        snap = telemetry.snapshot()
    except Exception:  # noqa: BLE001
        return None
    out = {}
    for mname, fam in snap.items():
        compact = {"type": fam["type"], "series": fam["series"][:max_series]}
        if "buckets" in fam:
            compact["buckets"] = fam["buckets"]
        out[mname] = compact
    return out or None


def _compile_delta(res, name, before, after):
    """Per-config warm-vs-cold evidence in the BENCH_*.json trajectory:
    seconds of fresh XLA compile the config paid, how many executables
    the shared disk cache served instead, and the time from config
    start to its first compiled step."""
    if not (before and after):
        return
    res[name + "_compile_s"] = round(
        after["backend_compile_s"] - before["backend_compile_s"], 3)
    res[name + "_fresh_compiles"] = (
        after["fresh_compiles"] - before["fresh_compiles"])
    res[name + "_disk_cache_hits"] = (
        after["disk_cache_hits"] - before["disk_cache_hits"])
    tts = after.get("time_to_first_step_s") or {}
    if tts:
        res[name + "_time_to_first_step_s"] = round(min(tts.values()), 3)


def _run_campaign_config(name, out_dir, small, deadline_ts):
    """ONE config in ONE child process (the campaign runner's unit of
    isolation): backend init, a `<name>.started` marker the moment the
    backend answered (the orchestrator's per-config deadline countdown
    anchors here — time spent WAITING for the chip grant is never
    charged to the config, and a child without the marker is never
    killed on a TPU backend, so the grant queue cannot be poisoned),
    then the config with in-process error capture + small-size retry.
    Exits 0 even on a recorded error — a nonzero exit means this child
    CRASHED, and the orchestrator records it as such."""
    out_path = os.path.join(out_dir, name + ".json")
    _child_setup_jax()
    import jax

    jax.devices()  # backend up = grant held (on a TPU backend)
    if time.time() > deadline_ts:
        # self-deadline BEFORE the marker: an orphaned grant-waiter
        # served after its round ended must exit silently — writing the
        # .started marker or any result file into the shared state dir
        # would be ingested by the NEXT round (its orchestrator would
        # misread the stale marker as its own child holding the grant
        # and kill a pure grant-waiter — the r03/r04 poisoning)
        print(f"campaign config {name}: round deadline passed before the "
              "backend was granted; exiting without results",
              file=sys.stderr)
        return
    with open(os.path.join(out_dir, name + ".started"), "w") as f:
        f.write(str(time.time()))
    fn, small_kw, _ = CONFIGS[name]
    before = _compile_snapshot()
    before_ds = _dispatch_snapshot()
    if before is not None:
        try:  # per-config time-to-first-step epoch
            from paddle_tpu.runtime import warmup

            warmup.reset_first_step()
        except Exception:  # noqa: BLE001
            pass
    try:
        res = fn(**small_kw) if small else fn()
        if small:
            res[name + "_small"] = True
    except Exception as e:  # noqa: BLE001 — record, keep going
        res = {name + "_error": f"{type(e).__name__}: {e}"[:300]}
        if not small and deadline_ts - time.time() > 90.0:
            # a deterministic full-size failure (OOM, shape bug) can
            # still contribute a measured small-size number
            try:
                retry = fn(**small_kw)
                retry[name + "_small"] = True
                res.update(retry)
            except Exception as e2:  # noqa: BLE001
                res[name + "_small_error"] = (
                    f"{type(e2).__name__}: {e2}"[:300])
    try:
        _compile_delta(res, name, before, _compile_snapshot())
    except Exception:  # noqa: BLE001 — metrics must not fail a result
        pass
    try:
        # op-level hit rates per config: perf-trajectory rounds
        # carry the WHY, not just the aggregate wall clock
        _dispatch_delta(res, name, before_ds, _dispatch_snapshot())
    except Exception:  # noqa: BLE001 — metrics must not fail a result
        pass
    try:
        # per-child registry snapshot into a SUBDIR (never merged into
        # the details dict — the orchestrator folds these into one
        # round-level telemetry_registry with _merge_registries)
        reg = _registry_snapshot()
        if reg:
            rdir = os.path.join(out_dir, "registry")
            os.makedirs(rdir, exist_ok=True)
            _write_out(os.path.join(rdir, name + ".json"), reg)
    except Exception:  # noqa: BLE001
        pass
    _write_out(out_path, res)


def _merge_registries(out_dir, max_series=20):
    """Fold the per-child registry snapshots into one round-level view
    (children are separate processes, so counter/histogram sums across
    them are real totals; gauges keep the last child's value). Plain
    dict math — the orchestrator never imports jax/paddle_tpu."""
    rdir = os.path.join(out_dir, "registry")
    try:
        names = sorted(os.listdir(rdir))
    except OSError:
        return None
    merged = {}
    for fname in names:
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(rdir, fname)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        for mname, fam in snap.items():
            dst = merged.setdefault(
                mname, {"type": fam.get("type"), "series": {}})
            if "buckets" in fam and "buckets" not in dst:
                dst["buckets"] = fam["buckets"]
            for s in fam.get("series", []):
                key = json.dumps(s.get("labels", {}), sort_keys=True)
                prev = dst["series"].get(key)
                if prev is None:
                    dst["series"][key] = dict(s)
                elif "bucket_counts" in s and "bucket_counts" in prev \
                        and len(s["bucket_counts"]) == \
                        len(prev["bucket_counts"]):
                    prev["bucket_counts"] = [
                        a + b for a, b in zip(prev["bucket_counts"],
                                              s["bucket_counts"])]
                    prev["sum"] = prev.get("sum", 0) + s.get("sum", 0)
                    prev["count"] = prev.get("count", 0) + s.get("count", 0)
                elif dst["type"] == "counter":
                    prev["value"] = prev.get("value", 0) + s.get("value", 0)
                else:  # gauge: last child wins
                    prev["value"] = s.get("value", prev.get("value"))
    out = {}
    for mname, fam in merged.items():
        compact = {"type": fam["type"],
                   "series": list(fam["series"].values())[:max_series]}
        if "buckets" in fam:
            compact["buckets"] = fam["buckets"]
        out[mname] = compact
    return out or None


# --------------------------------------------------------------------------
# orchestrator (never imports jax)
# --------------------------------------------------------------------------

def _collect(out_dir, details, keymap=None):
    """Merge every per-config result file written so far. `keymap`
    (key -> producing config name, i.e. the result filename) is the
    merge-time attribution used to scope small-run exclusion during
    baseline publishing — keys are not uniformly config-prefixed
    (flash_attention emits attn_*, generate emits decode_*), and a
    hand-maintained prefix table would silently drift."""
    try:
        names = os.listdir(out_dir)
    except OSError:
        return
    for fname in sorted(names):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(out_dir, fname)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        details.update(data)
        if keymap is not None:
            cfg = fname[:-len(".json")]
            for k in data:
                keymap[k] = cfg


def _collect_child_diagnostics(diag_dir, name, details, tail_n=15):
    """Evidence from a dead/killed config child: the newest postmortem
    bundle path (written by the child's SIGTERM handler or stall dump)
    and the final records of its flight-recorder spill (append-only, so
    even a SIGKILL leaves them). Plain file reads — the orchestrator
    never imports paddle_tpu. A dead child used to leave only a
    truncated `runner_error` stderr string."""
    def _mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    try:
        # newest by mtime, not filename: a config that spawned helper
        # subprocesses leaves bundles from several pids in this dir,
        # and the lexicographic order would rank by pid, not recency
        names = sorted((n for n in os.listdir(diag_dir)
                        if n.startswith("postmortem-")
                        and n.endswith(".json")),
                       key=lambda n: _mtime(os.path.join(diag_dir, n)))
    except OSError:
        return
    if names:
        details[name + "_bundle_path"] = os.path.join(diag_dir, names[-1])
    tail = []
    # oldest-written spill first, newest last: with several pids in one
    # dir (a config that spawned helpers), tail[-n] must come from the
    # most recently active process, not whichever filename sorts last
    spill_names = sorted(
        (n for n in os.listdir(diag_dir)
         if n.startswith("flight-") and n.endswith(".jsonl")),
        key=lambda n: _mtime(os.path.join(diag_dir, n)))
    for fname in spill_names:
        base = os.path.join(diag_dir, fname)
        # rotated generation first (a child killed right after a spill
        # rotation holds its recent history in the .1 file)
        for p in (base + ".1", base):
            try:
                with open(p) as f:
                    lines = f.readlines()
            except OSError:
                continue
            for line in lines:
                try:
                    tail.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line (the kill -9 contract)
    if tail:
        details[name + "_flight_tail"] = tail[-tail_n:]


def _error_payload(msg):
    return {"metric": "BERT-base MLM tokens/sec/chip (AMP O2 bf16)",
            "value": None, "unit": "tokens/sec", "vs_baseline": None,
            "error": msg[:300]}


def _emit(payload):
    print(json.dumps(payload), flush=True)


# measurement suffixes — the same vocabulary _publish_baseline uses to
# decide what is publishable perf data. Probe/env keys (device_count,
# probe_matmul) and per-config compile bookkeeping (*_compile_s,
# *_fresh_compiles) exist even for a fully wedged round and must not
# make it look like it measured anything.
_DATA_POINT_SUFFIXES = ("_per_sec", "_ms", "_mfu", "_tops")


def _count_data_points(details):
    """Perf measurements in the merged details — the round's actual
    yield. A round whose every config wedged must read as ZERO, not as
    'some bookkeeping keys exist'."""
    return sum(1 for k, v in details.items()
               if k.endswith(_DATA_POINT_SUFFIXES)
               and isinstance(v, (int, float))
               and not isinstance(v, bool))


def _result_file_path():
    return os.environ.get("BENCH_RESULT_PATH",
                          os.path.join(REPO, "BENCH_partial.json"))


_RESULT_TMP_SEQ = itertools.count()


def _write_result_file(payload):
    """Persist the latest payload to BENCH_partial.json (atomic rename)
    regardless of how the process exits. The stdout JSON line is the
    driver contract, but r02–r05 showed kill paths where the tail was
    lost — the file survives a lost tail, so a wedged config can no
    longer zero out a round silently. Updated on every streamed
    snapshot (not just final emit): a SIGKILL runs no handlers, and the
    file must hold THIS round's latest partials when it lands.

    The tmp name carries a per-call sequence number: the SIGTERM
    handler calls this ON TOP of an interrupted snapshot write in the
    same thread, and a pid-keyed tmp would let the two calls clobber
    one inode (the handler's final payload then torn by the outer
    frame's buffered flush). The handler os._exit()s, so its uniquely
    named write is the last one standing; the outer frame's orphan tmp
    is covered by the BENCH_partial.json* gitignore pattern."""
    path = _result_file_path()
    try:
        tmp = f"{path}.tmp.{os.getpid()}.{next(_RESULT_TMP_SEQ)}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        pass


# The driver records the stdout TAIL and parses the LAST JSON line, so
# the orchestrator streams a fresh snapshot line every time a result
# lands: a driver-side kill at ANY moment (even SIGKILL, which runs no
# handlers) still leaves the latest partials parseable in the tail —
# the r04 failure mode (rc=124, empty tail, a successful probe lost).
_FINAL_DONE = [False]

# main() installs its _partial_payload here so the __main__ BaseException
# wrapper can emit merged partials (not a bare error payload that would
# mask results already measured to disk) on kill paths other than SIGTERM
_PARTIAL_HOOK = [None]


def _emit_final(payload):
    """The one authoritative line; later callers (atexit after SIGTERM,
    the __main__ error wrapper after a natural end) must not emit a
    second, staler final line. The same payload lands in the partial
    results file unconditionally."""
    if _FINAL_DONE[0]:
        return
    _FINAL_DONE[0] = True
    _write_result_file(payload)
    _emit(payload)


def _headline_of(details, small_all):
    cfg_name, ref_key, metric, unit = _HEADLINE_CANDIDATES[0]
    value = None
    for cn, key, m, u in _HEADLINE_CANDIDATES:
        if details.get(key):
            cfg_name, ref_key, metric, unit = cn, key, m, u
            value = details[key]
            break
    if value and (details.get(cfg_name + "_small") or small_all):
        metric += " [small-config fallback]"
    return cfg_name, ref_key, metric, unit, value


def _build_payload(details, small_all, publish, keymap):
    """Assemble the JSON-line payload from merged details. `publish`
    gates the BASELINE.json write: only the natural end of a run may
    publish (a mid-run snapshot could publish a partial sweep).
    `keymap` is REQUIRED (merge-time key->config attribution from
    _collect): a call site that dropped it would publish an empty
    baseline and permanently block republishing — pass {} only if
    attribution is genuinely unavailable."""
    cfg_name, ref_key, metric, unit, value = _headline_of(details, small_all)
    baseline = _publish_baseline(details, cfg_name, ref_key, value,
                                 publish=publish, keymap=keymap)
    payload = {
        "metric": metric,
        "value": round(value, 1) if value else None,
        "unit": unit,
        "vs_baseline": round(baseline, 3)
        if (value and baseline is not None) else None,
        **{k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in details.items()},
    }
    payload["data_points"] = _count_data_points(details)
    return payload, value


def _publish_baseline(details, cfg_name, ref_key, value, publish=True,
                      keymap=None):
    """First full real-chip run publishes its numbers as the baseline so
    later rounds report a real vs_baseline ratio. Small-size numbers are
    never published and never compared against a full-size baseline —
    either direction poisons the ratio permanently. Smallness is scoped
    PER CONFIG: a late config that fell back to small (deadline
    pressure) must not block publishing the full headline's numbers —
    its own keys are simply excluded from the published set."""
    headline_small = bool(details.get(cfg_name + "_small"))
    # None until a real comparison exists: a ratio of 1.0 with nothing
    # published would read as "measured vs baseline" when it never was
    baseline = None
    baseline_path = os.path.join(REPO, "BASELINE.json")
    try:
        with open(baseline_path) as f:
            baseline_doc = json.load(f)
        published = baseline_doc.get("published", {})
        ref = published.get(ref_key)
        if value and ref:
            baseline = value / ref if not headline_small else None
        elif (publish and value and not published and not headline_small
              and os.environ.get("BENCH_SMALL", "0").lower() not in
              ("1", "true", "yes")
              and str(details.get("backend", "")).lower() in ("tpu", "axon")
              and details.get("bert_tokens_per_sec")):
            km = keymap or {}

            def _from_small_cfg(k):
                # unattributed keys (no result file, e.g. orchestrator
                # annotations) are conservatively NOT published
                cfg = km.get(k)
                return cfg is None or bool(details.get(cfg + "_small"))

            pub = {k: round(v, 2) for k, v in details.items()
                   if isinstance(v, float) and not _from_small_cfg(k)
                   and (k.endswith("_per_sec") or k.endswith("_ms")
                        or k.endswith("_mfu") or k.endswith("_tops"))}
            pub["device_kind"] = details.get("device_kind")
            # a baseline without the headline key can never be compared
            # against — writing one would permanently block republishing
            if ref_key in pub:
                baseline_doc["published"] = pub
                with open(baseline_path, "w") as f:
                    json.dump(baseline_doc, f, indent=2)
                baseline = 1.0  # this run IS the baseline
    except (OSError, ValueError):
        pass
    return baseline


def main():
    t_start = time.monotonic()
    budget_s = float(os.environ.get("BENCH_DEADLINE_S", 3300))
    deadline_ts = time.time() + budget_s
    out_dir = os.environ.get("BENCH_STATE_DIR",
                             os.path.join(REPO, ".bench_state"))
    # stale results from an earlier run must not masquerade as this run's
    # — _collect merges EVERY *.json in out_dir, so cleanup must cover
    # any config name (a prior run may have used a different CONFIGS
    # table), while still bounding the blast radius if BENCH_STATE_DIR
    # points somewhere shared
    if os.path.isdir(out_dir):
        for fname in os.listdir(out_dir):
            known = (fname.endswith((".json", ".started", ".stderr"))
                     or fname.startswith("runner_"))
            if known:
                try:
                    os.remove(os.path.join(out_dir, fname))
                except OSError:
                    pass
        rdir = os.path.join(out_dir, "registry")
        if os.path.isdir(rdir):
            for fname in os.listdir(rdir):
                try:
                    os.remove(os.path.join(rdir, fname))
                except OSError:
                    pass
        # per-config diagnostics (postmortem bundles + flight spills)
        # from a previous round: a stale bundle must not be attributed
        # to THIS round's kill
        ddir = os.path.join(out_dir, "diagnostics")
        if os.path.isdir(ddir):
            import shutil

            shutil.rmtree(ddir, ignore_errors=True)

    # a previous round's final payload must not masquerade as this
    # round's if we are killed before the first snapshot lands
    try:
        os.remove(_result_file_path())
    except OSError:
        pass

    def remaining():
        return budget_s - (time.monotonic() - t_start)

    small_all = os.environ.get("BENCH_SMALL", "0").lower() in ("1", "true",
                                                               "yes")
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    # cheapest-first (SNIPPETS campaign-runner order): with per-config
    # child isolation no hang can starve the rest, so the cheap probes
    # bank their numbers before the expensive headline configs run
    todo = sorted(CONFIGS, key=lambda n: CONFIGS[n][2])
    details = {}
    keymap = {}  # result key -> producing config (merge-time attribution)
    # state["proc"]/["name"] = the active config child (None between
    # spawns); state["probe"] marks the patient probe child, which is
    # NEVER killed early — a killed grant-waiter poisons the queue
    state = {"proc": None, "name": None, "probe": False}

    def _started_marker(name):
        return os.path.join(out_dir, name + ".started")

    def _child_started(name):
        return os.path.exists(_started_marker(name))

    def _killable(name):
        # a config child holding the backend (marker written) dies
        # safely — its session closes with the process and the grant
        # frees. Unstarted children are killable only off-TPU (no
        # grant queue to poison).
        return name is not None and (_child_started(name) or force_cpu
                                     or _backend_is_cpu())

    def _backend_is_cpu():
        return str(details.get("backend", "")).lower() in ("cpu",)

    def _partial_payload(tag):
        d = dict(details)
        _collect(out_dir, d, keymap)
        payload, value = _build_payload(d, small_all, publish=False,
                                        keymap=keymap)
        payload["partial"] = tag
        return payload, value

    def _on_sigterm(signum, frame):
        # the driver's timeout SIGTERMs the orchestrator; everything
        # measured so far must reach stdout before dying (r04 lost a
        # successful probe this way), and the runner child must be
        # terminated so its session closes and the grant releases.
        # os.write is the only reentrancy-safe emit: the signal may have
        # landed INSIDE a _snapshot_if_new print (print from a handler
        # then raises "reentrant call inside BufferedWriter"), and that
        # failure must not skip the child terminate below.
        value = None
        try:
            payload, value = _partial_payload("sigterm")
            if not _FINAL_DONE[0]:
                _FINAL_DONE[0] = True
                _write_result_file(payload)
                # leading \n: the signal may have interrupted a snapshot
                # print mid-line; appending to that unterminated prefix
                # would corrupt the last-line-wins tail
                os.write(1, ("\n" + json.dumps(payload) + "\n").encode())
        except Exception:  # noqa: BLE001 — cleanup must still run
            pass
        proc = state.get("proc")
        if proc is not None and proc.poll() is None:
            # kill-safety: the probe child and an unstarted TPU config
            # child are grant-queue WAITERS — killing one leaves an
            # unclaimed grant poisoning the queue for successors (the
            # r03/r04 wedge); orphaned, they die on their own. A child
            # that wrote its .started marker holds the grant and must
            # die so the session closes and the chip frees.
            try:
                if not state.get("probe") and _killable(state.get("name")):
                    proc.terminate()
                    try:
                        proc.wait(timeout=15.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
            except Exception:  # noqa: BLE001 — dying anyway
                pass
        os._exit(0 if value else 1)

    signal.signal(signal.SIGTERM, _on_sigterm)
    _PARTIAL_HOOK[0] = _partial_payload
    atexit.register(lambda: None if _FINAL_DONE[0]
                    else _emit_final(_partial_payload("atexit")[0]))

    reported = set()

    def _snapshot_if_new():
        """Stream an updated JSON line whenever a new result file lands
        (probe.json included — the early 'probe succeeded' signal)."""
        try:
            files = {f for f in os.listdir(out_dir)
                     if f.endswith(".json")}
        except OSError:
            return
        if files - reported:
            reported.update(files)
            payload = _partial_payload("running")[0]
            # keep the partials file as fresh as the stdout stream: a
            # SIGKILL (no handlers) must leave THIS round's snapshot,
            # not the previous round's final payload
            _write_result_file(payload)
            _emit(payload)

    os.makedirs(out_dir, exist_ok=True)

    def _wait_child(proc, name, cost_s):
        """Poll one config child to completion. Deadlines: the GLOBAL
        budget always applies; the PER-CONFIG deadline (cost estimate
        + 600s tunnel-compile slack) starts counting only once the
        child wrote its .started marker — grant-queue wait is free.
        Returns 'done' | 'killed' | 'orphaned'."""
        started_at = None
        spawned_at = time.monotonic()
        while True:
            try:
                proc.wait(timeout=min(5.0, max(1.0, remaining())))
                return "done"
            except subprocess.TimeoutExpired:
                pass
            _snapshot_if_new()
            if started_at is None and _child_started(name):
                started_at = time.monotonic()
            over_config = (started_at is not None
                           and time.monotonic() - started_at
                           > cost_s + 600.0)
            # off-TPU there is no grant to claim: a child that never
            # starts is wedged in import/init, not patiently waiting
            over_start = ((force_cpu or _backend_is_cpu())
                          and started_at is None
                          and time.monotonic() - spawned_at > 600.0)
            if remaining() <= 0.0 or over_config or over_start:
                if not _killable(name):
                    # TPU grant-waiter at the global deadline: orphan
                    # it (it exits on its own); killing would poison
                    # the grant queue for the next round
                    return "orphaned"
                proc.terminate()
                try:
                    proc.wait(timeout=30.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                if over_config:
                    details[name + "_error"] = (
                        f"hung >{int(cost_s + 600)}s mid-config; killed")
                elif over_start:
                    details[name + "_error"] = (
                        "backend init wedged; killed")
                else:
                    details["runner_killed_at_deadline"] = True
                    details.setdefault(
                        name + "_error",
                        "in flight when the deadline killed it")
                return "killed"

    # the patient probe child: backend liveness, never killed early
    # (see _on_sigterm). Its failure is recorded, not fatal — the
    # CPU-pinned configs still produce numbers on a dead tunnel.
    if remaining() > 90.0:
        err_path = os.path.join(out_dir, "runner_probe.stderr")
        with open(err_path, "wb") as err_f:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--probe",
                 "--out", os.path.join(out_dir, "probe.json")],
                cwd=REPO, stdout=subprocess.DEVNULL, stderr=err_f)
            state.update(proc=proc, name=None, probe=True)
            while True:
                try:
                    proc.wait(timeout=min(10.0, max(1.0, remaining())))
                    break
                except subprocess.TimeoutExpired:
                    if remaining() <= 0.0:
                        break  # orphaned: exits on its own
        state.update(proc=None, probe=False)
        if proc.poll() is not None and proc.returncode != 0:
            try:
                with open(err_path, "rb") as f:
                    tail = f.read()[-300:].decode("utf-8", "replace")
            except OSError:
                tail = ""
            _write_out(os.path.join(out_dir, "probe.json"),
                       {"probe_error":
                        f"probe child rc={proc.returncode}: {tail}"[:300]})
        _collect(out_dir, details, keymap)
        _snapshot_if_new()

    # the campaign: every config in its OWN child process, cheapest
    # first — one hung or crashing config can no longer zero out the
    # round (ROADMAP item 4). Children share the compile-cache dir
    # (exported by _child_setup_jax), so later children load what
    # earlier ones compiled.
    for name in todo:
        if details.get("runner_killed_at_deadline"):
            break
        fn, small_kw, full_cost_s = CONFIGS[name]
        if remaining() < 90.0:
            _write_out(os.path.join(out_dir, name + ".json"),
                       {name + "_skipped": "out of time budget"})
            continue
        small = small_all or remaining() < full_cost_s + 120.0
        args = ["--campaign-config", name,
                "--out-dir", out_dir,
                "--deadline-ts", str(deadline_ts)]
        if small:
            args.append("--small")
        err_path = os.path.join(out_dir, f"runner_{name}.stderr")
        # every child gets its own diagnostics dir: a deadline SIGTERM
        # makes it dump a postmortem bundle (all-thread stacks, dispatch
        # + fusion stats, flight-recorder tail) and even a SIGKILLed
        # child leaves its append-only flight spill — evidence instead
        # of a bare rc=124
        diag_dir = os.path.join(out_dir, "diagnostics", name)
        child_env = dict(os.environ,
                         PADDLE_TPU_DIAGNOSTICS_DIR=diag_dir)
        with open(err_path, "wb") as err_f:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)] + args,
                cwd=REPO, stdout=subprocess.DEVNULL, stderr=err_f,
                env=child_env)
            state.update(proc=proc, name=name, probe=False)
            outcome = _wait_child(proc, name, full_cost_s)
        state.update(proc=None, name=None)
        if outcome == "killed" or (outcome == "done"
                                   and proc.returncode != 0):
            _collect_child_diagnostics(diag_dir, name, details)
        if outcome == "done" and proc.returncode != 0:
            # a hard CRASH (our in-child error capture exits 0):
            # record rc + stderr tail; no retry — a deterministic
            # crasher must not starve the rest
            details["runner_crash_rc"] = proc.returncode
            details.setdefault(
                name + "_error",
                f"child crashed during this config (rc={proc.returncode})")
            try:
                with open(err_path, "rb") as f:
                    tail = f.read()[-400:].decode("utf-8", "replace")
                if tail.strip():
                    details["runner_error"] = tail
            except OSError:
                pass
        _collect(out_dir, details, keymap)
        _snapshot_if_new()
    _collect(out_dir, details, keymap)
    try:
        reg = _merge_registries(out_dir)
        if reg:
            details["telemetry_registry"] = reg
    except Exception:  # noqa: BLE001 — observability must not fail a round
        pass
    for name in todo:
        # result keys are not all name-prefixed (flash_attention -> attn_*)
        # so presence is judged by the per-config result file + markers
        if (not os.path.exists(os.path.join(out_dir, name + ".json"))
                and name + "_error" not in details):
            details[name + "_skipped"] = "never attempted"

    # headline = BERT; fall back to the next real number on tunnel flakes.
    # If nothing measured, keep the documented BERT label with value null.
    # A number from a small-size retry is reported but LABELED as such so
    # no cross-round comparison mistakes it for the full config.
    payload, value = _build_payload(details, small_all, publish=True,
                                    keymap=keymap)
    _emit_final(payload)
    if value is None or payload.get("data_points", 0) == 0:
        # a numberless round must look like failure to the driver. The
        # data_points clause states the zero-data contract explicitly:
        # today a non-None headline implies >= 1 data point (headline
        # keys are *_per_sec), so it only adds protection if a future
        # headline key leaves the measurement-suffix vocabulary
        raise SystemExit(1)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", action="store_true")
    ap.add_argument("--config", choices=list(CONFIGS))
    ap.add_argument("--out")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--campaign-config", choices=list(CONFIGS),
                    help="internal: one config as a campaign child")
    ap.add_argument("--out-dir")
    ap.add_argument("--deadline-ts", type=float)
    cli = ap.parse_args()
    if cli.campaign_config:
        _run_campaign_config(
            cli.campaign_config,
            cli.out_dir or os.path.join(REPO, ".bench_state"),
            cli.small, cli.deadline_ts or (time.time() + 3300))
    elif cli.probe:
        _run_probe(cli.out)
    elif cli.config:
        _run_config(cli.config, cli.out, cli.small)
    else:
        try:
            main()
        except SystemExit:
            raise
        except BaseException as e:  # noqa: BLE001 — the JSON line must print
            payload = _error_payload(f"{type(e).__name__}: {e}")
            if _PARTIAL_HOOK[0] is not None:
                try:  # merge whatever reached disk before the exception
                    payload, _ = _PARTIAL_HOOK[0]("error")
                    payload["error"] = f"{type(e).__name__}: {e}"[:300]
                except Exception:  # noqa: BLE001
                    payload = _error_payload(f"{type(e).__name__}: {e}")
            _emit_final(payload)
            raise SystemExit(1)
