"""Real-chip throughput bench (SURVEY §6 / BASELINE.json configs).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...details}

Headline metric: BERT-base MLM tokens/sec/chip (AMP O2 bf16, whole-step
jit with donated buffers). Details carry ResNet50 static-Executor
imgs/sec, LeNet Model.fit imgs/sec, and the flash-attention A/B.
vs_baseline is the ratio against BASELINE.json's published numbers when
present (1.0 otherwise — round 1 published none).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

import jax  # noqa: E402

# persistent XLA compile cache: BERT-base/ResNet50 compiles are minutes on
# the tunneled chip; cache them across bench runs/rounds. sitecustomize
# imports jax before this module, so the env var would be ignored — set it
# through the live config instead.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__) or ".",
                               ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _sync(x):
    """Force materialization: np.asarray round-trips through the host, the
    only sync the axon tunnel honors (block_until_ready returns early)."""
    return np.asarray(jax.tree_util.tree_leaves(x)[0])


# peak dense bf16 FLOP/s per chip, by device_kind substring (public specs)
_PEAK_BF16 = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _chip_peak_flops():
    """Peak bf16 FLOP/s of the attached chip, or None when the device kind
    is not a known TPU (an 'MFU' against a guessed peak is noise)."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return None
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return 197e12 if "tpu" in kind else None  # v5e = BASELINE north star


def _init_backend_with_retry(attempts=3, backoff_s=30.0):
    """Round 2 died because one tunnel flake at jax.default_backend()
    crashed the whole bench (BENCH_r02 rc=1). Retry backend init with
    backoff; on final failure return an error string instead of raising so
    main() still prints its one JSON line."""
    last = None
    for i in range(attempts):
        try:
            return {"backend": jax.default_backend(),
                    "device_count": jax.device_count(),
                    "device_kind": jax.devices()[0].device_kind}, None
        except Exception as e:  # noqa: BLE001
            last = str(e)[:300]
            if i + 1 < attempts:
                time.sleep(backoff_s * (i + 1))
                try:
                    # jax caches backend-init FAILURE too; without this the
                    # retry would re-raise the cached error instantly
                    import jax.extend.backend

                    jax.extend.backend.clear_backends()
                except Exception:  # noqa: BLE001
                    pass
    return None, last


def bench_bert(batch=16, seq=128, steps=30, warmup=5):
    """BERT-base MLM, AMP O2 (bf16 weights, f32 norms), fused jitted step."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM

    paddle.seed(0)
    cfg = BertConfig(dropout=0.0, attention_dropout=0.0)  # base config
    model = BertForMaskedLM(cfg)
    paddle.amp.decorate(model, level="O2")  # bf16 weights, norms f32
    model.eval()  # dropout off; stats frozen (MLM has no BN)

    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            # tape off: jax.value_and_grad is the single AD level (the
            # eager tape nesting inside it would second-differentiate the
            # Pallas custom_vjp forward — same pattern as hapi/model.py:64)
            with paddle.no_grad():
                out, _ = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, None, Tensor(labels))
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st, jnp.float32(1e-4),
                                             meta=meta)
        return new_p, new_s, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    lowered = jit_step.lower(params, states, ids, labels)
    # f64 scan on the LOCAL pre-optimization MLIR: fetching the optimized
    # HLO text of a whole BERT train step back through the tunnel is
    # hundreds of MB and dwarfs the compile itself. Scalar tensor<f64>
    # literals (weak-typed python floats under x64, converted in place)
    # are free; SHAPED f64 arrays are the perf cliff.
    import re

    # any shaped tensor (static `2x...` or dynamic `?x...`) ends in `xf64`
    f64_free = not re.search(r"tensor<[^>]*xf64>", lowered.as_text())
    compiled = lowered.compile()
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        step_flops = float(cost.get("flops", 0)) if cost else 0.0
    except Exception:  # noqa: BLE001 — cost analysis optional per backend
        step_flops = 0.0

    for _ in range(warmup):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss if warmup else params)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    dt = time.perf_counter() - t0
    out = {
        "bert_tokens_per_sec": steps * batch * seq / dt,
        "bert_step_ms": dt / steps * 1e3,
        "bert_loss": float(loss),
        "f64_free": f64_free,
    }
    peak = _chip_peak_flops()
    if step_flops > 0 and peak:
        # MFU = model FLOPs per step / step time / chip peak bf16 FLOPs
        out["bert_mfu"] = (step_flops / (dt / steps)) / peak
    return out


def bench_gpt(batch=8, seq=512, steps=20, warmup=3):
    """GPT-2 small causal-LM train step (bf16 weights, donated buffers) —
    the single-chip slice of the BASELINE 'GPT-2 sharding+PP' config."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_position=seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    paddle.amp.decorate(model, level="O2")
    model.eval()
    params = {k: p._value for k, p in model.named_parameters()
              if not p.stop_gradient}
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    meta = opt.param_meta({k: p for k, p in model.named_parameters()
                           if not p.stop_gradient})
    states = opt.functional_init_states(params)

    def step(pv, st, ids, labels):
        def loss_of(p):
            with paddle.no_grad():
                out = model.functional_call(
                    {k: Tensor(v) for k, v in p.items()},
                    Tensor(ids), None, Tensor(labels))[0]
            loss = out[0] if isinstance(out, (list, tuple)) else out
            return loss._value.astype(jnp.float32)
        loss, grads = jax.value_and_grad(loss_of)(pv)
        new_p, new_s = opt.functional_update(pv, grads, st,
                                             jnp.float32(1e-4), meta=meta)
        return new_p, new_s, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    for _ in range(warmup):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, states, loss = jit_step(params, states, ids, labels)
    _sync(loss)
    dt = time.perf_counter() - t0
    return {"gpt_tokens_per_sec": steps * batch * seq / dt,
            "gpt_step_ms": dt / steps * 1e3,
            "gpt_loss": float(loss)}


def bench_resnet50(batch=64, steps=20, warmup=3):
    """ResNet50 static-graph Executor (single-device fp32)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [batch, 3, 224, 224], "float32")
            y = paddle.static.data("y", [batch], "int64")
            logits = resnet50(num_classes=100)(x)
            loss = nn.functional.cross_entropy(logits, y)
            paddle.optimizer.Momentum(learning_rate=0.1,
                                      momentum=0.9).minimize(loss)
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        # device-resident feeds: measure the train step, not the tunnel's
        # host->device bandwidth (input overlap is bench_dataloader's job)
        from paddle_tpu.core.tensor import Tensor as _T

        xs = _T(rng.randn(batch, 3, 224, 224).astype(np.float32))
        ys = _T(rng.randint(0, 100, batch).astype(np.int64))
        for _ in range(warmup):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])
        dt = time.perf_counter() - t0
    finally:
        paddle.disable_static()
    return {"resnet50_imgs_per_sec": steps * batch / dt,
            "resnet50_step_ms": dt / steps * 1e3}


def bench_lenet(batch=256, steps=30, warmup=3):
    """LeNet dygraph Model.fit path (whole-step-jitted train_batch)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(parameters=model.parameters()),
                  nn.CrossEntropyLoss())
    from paddle_tpu.core.tensor import Tensor as _T

    rng = np.random.RandomState(0)
    xs = _T(rng.randn(batch, 1, 28, 28).astype(np.float32))
    ys = _T(rng.randint(0, 10, (batch, 1)).astype(np.int64))
    for _ in range(warmup):
        model.train_batch([xs], [ys])
    t0 = time.perf_counter()
    for _ in range(steps):
        model.train_batch([xs], [ys])
    dt = time.perf_counter() - t0
    return {"lenet_imgs_per_sec": steps * batch / dt}


def bench_generate(batch=8, prompt=32, new_tokens=96):
    """Jitted static-shape decode throughput (GPT-2 small, greedy)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(max_position=prompt + new_tokens,
                                     dropout=0.0))
    paddle.amp.decorate(model, level="O2")
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 50304, (batch, prompt)))
    out = model.generate(ids, max_new_tokens=new_tokens)  # compile
    _sync(out._value)
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=new_tokens)
    _sync(out._value)
    dt = time.perf_counter() - t0
    return {"decode_tokens_per_sec": batch * new_tokens / dt,
            "decode_ms_per_token": dt / new_tokens * 1e3}


def bench_flash_attention(batch=4, heads=12, seq=512, dim=64, iters=50):
    """Pallas flash attention vs XLA softmax attention, fwd+bwd."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _xla_attention
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    rng = np.random.RandomState(0)
    shape = (batch * heads, seq, dim)
    q, k, v = (jnp.asarray(rng.randn(*shape).astype(np.float32))
               for _ in range(3))

    def xla_loss(q, k, v):
        out, _ = _xla_attention(q[None], k[None], v[None], None, 0.0, None,
                                True)
        return (out ** 2).mean()

    def flash_loss(q, k, v):
        return (flash_attention_raw(q, k, v, True) ** 2).mean()

    res = {}
    for name, fn in [("xla", xla_loss), ("flash", flash_loss)]:
        try:
            g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
            _sync(g(q, k, v))
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(q, k, v)
            _sync(out)
            res[f"attn_{name}_ms"] = (time.perf_counter() - t0) / iters * 1e3
        except Exception as e:  # noqa: BLE001
            res[f"attn_{name}_ms"] = None
            res[f"attn_{name}_error"] = str(e)[:200]
    return res


def bench_dataloader(n=512, batch=64, shape=(3, 224, 224), epochs=3):
    """Input pipeline A/B: thread-prefetch DataLoader vs the C++ staging
    ring (csrc/staging_pool.cpp) — imgs/sec of collate+transfer."""
    import paddle_tpu as paddle

    class SynthDataset(paddle.io.Dataset):
        rng = np.random.RandomState(0)
        base = rng.randn(32, *shape).astype(np.float32)

        def __len__(self):
            return n

        def __getitem__(self, i):
            # simulate decode/augment work: flip + normalize
            img = self.base[i % 32]
            img = img[..., ::-1] * (1.0 / 255.0) - 0.5
            return np.ascontiguousarray(img), np.int64(i % 10)

    res = {}
    for name, kw in [("threads", {}), ("staging", {"use_staging_pool": True})]:
        loader = paddle.io.DataLoader(SynthDataset(), batch_size=batch,
                                      num_workers=4, **kw)
        for x, _ in loader:  # warm (compile/allocate/pool build)
            pass
        t0 = time.perf_counter()
        count = 0
        for _ in range(epochs):
            for x, _ in loader:
                count += int(x.shape[0])
        _sync(x._value)
        res[f"dataloader_{name}_imgs_per_sec"] = count / (
            time.perf_counter() - t0)
    return res


_HEADLINE_CANDIDATES = [
    ("bert_tokens_per_sec",
     "BERT-base MLM tokens/sec/chip (AMP O2 bf16)", "tokens/sec"),
    ("resnet50_imgs_per_sec",
     "ResNet50 train imgs/sec/chip (static Executor, fp32)", "imgs/sec"),
    ("lenet_imgs_per_sec", "LeNet Model.fit imgs/sec/chip", "imgs/sec"),
]


def _error_payload(msg):
    return {"metric": "BERT-base MLM tokens/sec/chip (AMP O2 bf16)",
            "value": None, "unit": "tokens/sec", "vs_baseline": None,
            "error": msg[:300]}


def main():
    details = {}
    # backend init is the observed hang point (jax.devices() can block
    # forever on a dead tunnel, never raising): give it a short fuse,
    # then re-arm the long whole-run deadline once a backend exists
    init_watchdog = _arm_watchdog(details, deadline_s=float(
        os.environ.get("BENCH_INIT_DEADLINE_S", 600)))
    backend_info, backend_err = _init_backend_with_retry()
    init_watchdog.cancel()
    _arm_watchdog(details)
    if backend_info is None:
        _emit(_error_payload(
            f"backend init failed after retries: {backend_err}"))
        return
    details.update(backend_info)
    small = os.environ.get("BENCH_SMALL", "0").lower() in ("1", "true",
                                                           "yes")
    benches = [
        (bench_bert, {"batch": 2, "seq": 32, "steps": 2, "warmup": 1}),
        (bench_resnet50, {"batch": 2, "steps": 2, "warmup": 1}),
        (bench_lenet, {"batch": 8, "steps": 2, "warmup": 1}),
        (bench_gpt, {"batch": 1, "seq": 32, "steps": 1, "warmup": 1}),
        (bench_generate, {"batch": 1, "prompt": 4, "new_tokens": 4}),
        (bench_flash_attention, {"batch": 1, "heads": 2, "seq": 128,
                                 "iters": 2}),
        (bench_dataloader, {"n": 32, "batch": 8, "epochs": 1}),
    ]
    for bench, small_kw in benches:
        try:
            details.update(bench(**small_kw) if small else bench())
        except Exception as e:  # noqa: BLE001
            details[bench.__name__ + "_error"] = str(e)[:300]

    # headline = BERT; fall back to the next real number on tunnel flakes.
    # If nothing measured, keep the documented BERT label with value null.
    candidates = _HEADLINE_CANDIDATES
    ref_key, metric, unit = candidates[0]
    value = None
    for key, m, u in candidates:
        if details.get(key):
            ref_key, metric, unit = key, m, u
            value = details[key]
            break
    baseline = 1.0
    baseline_path = os.path.join(os.path.dirname(__file__) or ".",
                                 "BASELINE.json")
    try:
        with open(baseline_path) as f:
            baseline_doc = json.load(f)
        published = baseline_doc.get("published", {})
        ref = published.get(ref_key)
        if value and ref:
            baseline = value / ref
        elif (value and not published and details.get("backend") == "tpu"
              and details.get("bert_tokens_per_sec")):
            # first real-chip run WITH the headline metric: publish the
            # measured numbers so later rounds report a real vs_baseline
            # ratio (a partial run must not lock in a baseline missing
            # the headline — vs_baseline would then read 1.0 forever)
            pub = {k: round(v, 2) for k, v in details.items()
                   if isinstance(v, float) and (
                       k.endswith("_per_sec") or k.endswith("_ms")
                       or k.endswith("_mfu"))}
            pub["device_kind"] = details.get("device_kind")
            baseline_doc["published"] = pub
            with open(baseline_path, "w") as f:
                json.dump(baseline_doc, f, indent=2)
    except (OSError, ValueError):
        pass

    _emit({
        "metric": metric,
        "value": round(value, 1) if value else None,
        "unit": unit,
        "vs_baseline": round(baseline, 3),
        **{k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in details.items()},
    })


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _arm_watchdog(details, deadline_s=None):
    """A tunnel hang mid-bench (device sync blocking forever) would leave
    the driver with NO JSON line; after the deadline, emit whatever was
    measured and hard-exit. Hard-exit is required: a wedged device thread
    ignores normal interpreter shutdown."""
    import threading

    if deadline_s is None:
        deadline_s = float(os.environ.get("BENCH_DEADLINE_S", 2400))

    def fire():
        snap = dict(details)  # main thread may still be mutating
        payload = _error_payload(
            f"watchdog: bench exceeded {deadline_s:.0f}s (device hang?); "
            "emitting partial results")
        payload.update({k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in snap.items()})
        for key, metric, unit in _HEADLINE_CANDIDATES:
            if snap.get(key):
                payload.update(metric=metric, unit=unit,
                               value=round(snap[key], 1))
                break
        _emit(payload)
        os._exit(0)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    return t


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — the JSON line must ALWAYS print
        _emit(_error_payload(f"{type(e).__name__}: {e}"))
        raise SystemExit(0)
